"""Mixture-of-experts layer + expert parallelism (net-new vs the reference,
the ``ep`` member of the dp/tp/pp/sp/ep mesh-axis family). Correctness bars:
top-k routing semantics, aux-loss accumulation into the training objective,
gradient check of the full layer, and expert-sharded == replicated training."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                DataSet, Adam, Sgd)
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, MoEDenseLayer,
                                               OutputLayer)
from deeplearning4j_tpu.nn.losses import LossFunction
from deeplearning4j_tpu.parallel import (EXPERT_AXIS, expert_rules,
                                         expert_parallel_step, make_mesh,
                                         replicated)


def _moe_net(n_in=6, n_out=4, experts=4, top_k=2, aux=0.0, seed=5,
             updater=None):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(updater or Sgd(learning_rate=0.1))
            .activation("identity")
            .list()
            .layer(MoEDenseLayer(n_in=n_in, n_out=8, num_experts=experts,
                                 top_k=top_k, aux_loss_weight=aux,
                                 activation="relu"))
            .layer(OutputLayer(n_in=8, n_out=n_out, activation="softmax",
                               loss=LossFunction.MCXENT))
            .build())
    return MultiLayerNetwork(conf).init()


def test_moe_forward_topk_routing_semantics():
    net = _moe_net()
    impl = net.impls[0]
    p = net.params["0"]
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(7, 6)), jnp.float32)
    gates, probs = impl._route(x.astype(jnp.float32), p["Wg"])
    g = np.asarray(gates)
    # exactly top_k nonzero gates per token, summing to 1
    assert (np.count_nonzero(g, axis=1) == 2).all()
    np.testing.assert_allclose(g.sum(axis=1), 1.0, rtol=1e-5)
    # the nonzero entries are the 2 largest router probs
    pr = np.asarray(probs)
    for i in range(g.shape[0]):
        top2 = set(np.argsort(pr[i])[-2:])
        assert set(np.nonzero(g[i])[0]) == top2


def test_moe_topk_exact_on_tied_probs():
    """An all-zero row gives a uniform router softmax; the index-based mask
    must still gate exactly top_k experts (a threshold mask would gate all)."""
    net = _moe_net()
    impl = net.impls[0]
    p = net.params["0"]
    x = jnp.zeros((3, 6), jnp.float32)
    gates, _ = impl._route(x, p["Wg"])
    g = np.asarray(gates)
    assert (np.count_nonzero(g, axis=1) == 2).all()
    np.testing.assert_allclose(g.sum(axis=1), 1.0, rtol=1e-5)


def test_moe_output_matches_manual_dense_dispatch():
    net = _moe_net(top_k=4)  # top_k == E: gates are the full softmax
    impl = net.impls[0]
    p = net.params["0"]
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(5, 6)), jnp.float32)
    y, _ = impl.forward(p, {}, x)
    probs = np.asarray(jax.nn.softmax(np.asarray(x) @ np.asarray(p["Wg"]),
                                      axis=-1))
    W, b = np.asarray(p["W"]), np.asarray(p["b"])
    want = np.zeros((5, 8), np.float32)
    for e in range(4):
        want += probs[:, e:e + 1] * (np.asarray(x) @ W[e] + b[e])
    np.testing.assert_allclose(np.asarray(y), np.maximum(want, 0.0),
                               rtol=1e-4, atol=1e-5)


def test_moe_aux_loss_enters_objective():
    rng = np.random.default_rng(2)
    f = rng.normal(size=(16, 6)).astype(np.float32)
    l = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)]
    net0 = _moe_net(aux=0.0)
    net1 = _moe_net(aux=10.0)  # big weight → visibly different score
    s0 = float(net0.score(DataSet(f, l)))
    s1 = float(net1.score(DataSet(f, l)))
    assert s1 > s0 + 0.1, (s0, s1)  # aux = w * E * Σ f·P ≥ w * 1


def _f64_moe_net(top_k, aux, seed=9):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(learning_rate=1.0))
            .dtype("float64").compute_dtype("float64")
            .activation("identity")
            .list()
            .layer(MoEDenseLayer(n_in=6, n_out=8, num_experts=4, top_k=top_k,
                                 aux_loss_weight=aux, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=4, activation="softmax",
                               loss=LossFunction.MCXENT))
            .build())
    return MultiLayerNetwork(conf).init()


def test_moe_gradient_check_dense_routing():
    """top_k == E: routing is smooth softmax everywhere, so EVERY param —
    router included — must pass the central-difference check."""
    from deeplearning4j_tpu.nn.gradientcheck import (GradientCheckUtil,
                                                     double_precision)
    with double_precision():
        net = _f64_moe_net(top_k=4, aux=0.0)
        rng = np.random.default_rng(3)
        ds = DataSet(rng.normal(size=(8, 6)),
                     np.eye(4)[rng.integers(0, 4, 8)].astype(np.float64))
        assert GradientCheckUtil.check_gradients(net, ds, print_results=True)


def test_moe_gradient_check_topk_experts():
    """top_k < E: the loss is piecewise-smooth in the ROUTER (gate support
    changes discontinuously at top-k boundaries, and the aux loss's argmax
    fraction is piecewise constant), so the router is excluded — the expert
    weights/biases flow smoothly through the fixed gates and must pass."""
    from deeplearning4j_tpu.nn.gradientcheck import (GradientCheckUtil,
                                                     double_precision)
    with double_precision():
        net = _f64_moe_net(top_k=2, aux=1e-2)
        rng = np.random.default_rng(3)
        ds = DataSet(rng.normal(size=(8, 6)),
                     np.eye(4)[rng.integers(0, 4, 8)].astype(np.float64))
        assert GradientCheckUtil.check_gradients(net, ds, print_results=True,
                                                 exclude={"Wg"})


def test_moe_trains_and_improves():
    rng = np.random.default_rng(4)
    f = rng.normal(size=(64, 6)).astype(np.float32)
    labels = (f[:, 0] + f[:, 1] > 0).astype(int)
    l = np.eye(4, dtype=np.float32)[labels]
    net = _moe_net(aux=1e-2, updater=Adam(learning_rate=5e-3))
    ds = DataSet(f, l)
    s0 = float(net.score(ds))
    for _ in range(60):
        net.fit(ds)
    assert float(net.score(ds)) < s0 * 0.6


def test_expert_parallel_matches_replicated_training():
    """The EP-sharded jitted step must produce the same params as the
    unsharded step (the TPU analogue of the reference's cuDNN-vs-builtin
    cross-checks)."""
    mesh = make_mesh(jax.devices()[:4], axes=(EXPERT_AXIS,))
    net_a = _moe_net(seed=21)
    net_b = _moe_net(seed=21)
    rules = expert_rules(net_a)
    assert any("/W$" in k for k in rules), rules

    step, place = expert_parallel_step(net_a, mesh)
    place(net_a)
    rng = np.random.default_rng(5)
    f = jnp.asarray(rng.normal(size=(8, 6)), jnp.float32)
    l = jnp.asarray(np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)])
    it = jax.device_put(jnp.asarray(0, jnp.int32), replicated(mesh))
    key = jax.device_put(jax.random.PRNGKey(0), replicated(mesh))
    pa, sa, ua, loss_a = step(net_a.params, net_a.states, net_a.updater_state,
                              it, key, f, l, None, None)

    raw = jax.jit(net_b._raw_step(False))
    pb, sb, ub, loss_b = raw(net_b.params, net_b.states, net_b.updater_state,
                             jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
                             f, l, None, None)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_moe_and_iterations_serde_round_trip(tmp_path):
    """MoEDenseLayer config + iterations survive JSON and ModelSerializer
    round-trips (reference config-serde + ModelSerializer contracts)."""
    import os
    import jax
    from deeplearning4j_tpu.nn.conf import MultiLayerConfiguration
    from deeplearning4j_tpu.utils.model_serializer import ModelSerializer

    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(Adam(learning_rate=1e-3)).activation("relu")
            .iterations(4)
            .list()
            .layer(MoEDenseLayer(n_in=6, n_out=8, num_experts=4, top_k=2,
                                 aux_loss_weight=0.01))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    conf2 = MultiLayerConfiguration.from_json(conf.to_json())
    assert conf2.global_conf.iterations == 4
    l0 = conf2.layers[0]
    assert (type(l0).__name__, l0.num_experts, l0.top_k) \
        == ("MoEDenseLayer", 4, 2)

    net = MultiLayerNetwork(conf2).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(8, 6)).astype(np.float32),
                 np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])
    net.fit(ds)
    assert net.iteration_count == 4  # scanned iterations honored post-serde

    p = os.path.join(str(tmp_path), "moe.zip")
    ModelSerializer.write_model(net, p)
    net2 = ModelSerializer.restore_multi_layer_network(p)
    for a, b in zip(jax.tree_util.tree_leaves(net.params),
                    jax.tree_util.tree_leaves(net2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_moe_in_computation_graph_aux_loss_and_training():
    """MoEDenseLayer inside a ComputationGraph: aux loss flows through the
    graph ctx into the objective, EP rules find vertex-named params, and the
    graph trains."""
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    def build(aux):
        g = (NeuralNetConfiguration.builder().seed(11)
             .updater(Sgd(learning_rate=0.1)).activation("identity")
             .graph_builder().add_inputs("in"))
        g.add_layer("moe", MoEDenseLayer(n_in=6, n_out=8, num_experts=4,
                                         top_k=2, aux_loss_weight=aux,
                                         activation="relu"), "in")
        g.add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                       loss="mcxent"), "moe")
        g.set_outputs("out")
        return ComputationGraph(g.build()).init()

    rng = np.random.default_rng(8)
    f = rng.normal(size=(16, 6)).astype(np.float32)
    l = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    ds = DataSet(f, l)

    net0, net1 = build(0.0), build(10.0)
    assert float(net1.score(ds)) > float(net0.score(ds)) + 0.1  # aux in loss

    from deeplearning4j_tpu.parallel import expert_rules
    rules = expert_rules(net0)
    assert any(k.startswith("^moe") for k in rules), rules

    s0 = float(net0.score(ds))
    for _ in range(30):
        net0.fit(ds)
    assert float(net0.score(ds)) < s0

    # EP-sharded CG step == replicated step
    net_a, net_b = build(1e-2), build(1e-2)
    mesh = make_mesh(jax.devices()[:4], axes=(EXPERT_AXIS,))
    step, place = expert_parallel_step(net_a, mesh)
    place(net_a)
    it = jax.device_put(jnp.asarray(0, jnp.int32), replicated(mesh))
    key = jax.device_put(jax.random.PRNGKey(0), replicated(mesh))
    pa, _, _, loss_a = step(net_a.params, net_a.states, net_a.updater_state,
                            it, key, (jnp.asarray(f),), (jnp.asarray(l),),
                            None, None)
    raw = jax.jit(net_b._raw_step(False))
    pb, _, _, loss_b = raw(net_b.params, net_b.states, net_b.updater_state,
                           jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
                           (jnp.asarray(f),), (jnp.asarray(l),), None, None)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


# ------------------------------------------------------ sparse dispatch
def _moe_impl(capacity_factor, top_k=2, experts=4, n_in=6, n_out=8, seed=5):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(learning_rate=0.1)).activation("identity")
            .list()
            .layer(MoEDenseLayer(n_in=n_in, n_out=n_out, num_experts=experts,
                                 top_k=top_k, capacity_factor=capacity_factor,
                                 activation="identity"))
            .layer(OutputLayer(n_in=n_out, n_out=4, activation="softmax",
                               loss=LossFunction.MCXENT))
            .build())
    net = MultiLayerNetwork(conf).init()
    return net.impls[0], net.params["0"]


def test_moe_sparse_dispatch_matches_dense_oracle():
    """With ample capacity (no drops) the capacity-factor dispatch must equal
    the dense gate-masked path token for token (VERDICT item 4 'done'
    criterion: dispatch-vs-dense output parity)."""
    impl_s, p = _moe_impl(capacity_factor=4.0)
    impl_d, _ = _moe_impl(capacity_factor=0.0)
    rng = np.random.default_rng(7)
    x = jnp.asarray(rng.normal(size=(33, 6)), jnp.float32)  # odd n on purpose
    ys, _ = impl_s.forward(p, {}, x, train=True)
    yd, _ = impl_d.forward(p, {}, x)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(yd),
                               rtol=1e-4, atol=1e-5)


def test_moe_sparse_dispatch_grads_match_dense_oracle():
    impl_s, p = _moe_impl(capacity_factor=4.0)
    impl_d, _ = _moe_impl(capacity_factor=0.0)
    rng = np.random.default_rng(9)
    x = jnp.asarray(rng.normal(size=(16, 6)), jnp.float32)

    def loss(params, impl):
        y, _ = impl.forward(params, {}, x, train=True)
        return jnp.sum(y ** 2)

    gs = jax.grad(loss)(p, impl_s)
    gd = jax.grad(loss)(p, impl_d)
    for ks in gs:
        np.testing.assert_allclose(np.asarray(gs[ks]), np.asarray(gd[ks]),
                                   rtol=1e-3, atol=1e-4, err_msg=ks)


def test_moe_sparse_overflow_drops_lowest_gate_assignments():
    """At capacity_factor=tiny every expert keeps only its first C slot-major
    (highest-gate-rank first) assignments; dropped pairs contribute zero, so
    the output is bounded and finite, and differs from dense."""
    impl_s, p = _moe_impl(capacity_factor=1e-6, top_k=2)
    impl_d, _ = _moe_impl(capacity_factor=0.0, top_k=2)
    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(64, 6)), jnp.float32)
    ys, _ = impl_s.forward(p, {}, x, train=True)
    yd, _ = impl_d.forward(p, {}, x)
    assert np.isfinite(np.asarray(ys)).all()
    assert float(np.max(np.abs(np.asarray(ys)))) <= \
        float(np.max(np.abs(np.asarray(yd)))) * 2 + 1.0
    assert float(np.max(np.abs(np.asarray(ys) - np.asarray(yd)))) > 0


def test_moe_sparse_dispatch_flops_drop():
    """XLA cost-analysis FLOPs must drop ≈E/top_k-fold vs the dense path
    (VERDICT item 4 'done' criterion). Config sized so the O(n·E·C·F)
    dispatch einsums are small next to the E·C·F·O expert compute."""
    # dispatch/combine einsums cost ≈ (n/O + n/F) of the expert compute, so
    # keep tokens ≪ features for the asymptotic E/k drop to dominate
    E, k, n, F, O = 8, 1, 128, 1024, 1024
    impl_s, p = _moe_impl(capacity_factor=1.0, top_k=k, experts=E,
                          n_in=F, n_out=O)
    impl_d, _ = _moe_impl(capacity_factor=0.0, top_k=k, experts=E,
                          n_in=F, n_out=O)
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.normal(size=(n, F)), jnp.float32)

    def flops(impl):
        from deeplearning4j_tpu.compat import cost_analysis

        fn = lambda params: impl.forward(params, {}, x, train=True)[0]
        ca = cost_analysis(jax.jit(fn).lower(p).compile())
        return float(ca.get("flops", 0.0))

    fd, fs = flops(impl_d), flops(impl_s)
    assert fd > 0 and fs > 0
    # dense ≈ 2nEFO; sparse ≈ 2ECFO + dispatch overhead. Demand ≥ E/k · 1/2.
    assert fs < fd / (E / k) * 2.0, (fd, fs)
    assert fd / fs > E / k / 2, (fd, fs, fd / fs)


def test_moe_sparse_expert_parallel_matches_replicated():
    """Sparse dispatch under EP sharding == replicated sparse step (the EP
    dryrun criterion from VERDICT item 4)."""
    def make():
        conf = (NeuralNetConfiguration.builder().seed(23)
                .updater(Sgd(learning_rate=0.1)).activation("identity")
                .list()
                .layer(MoEDenseLayer(n_in=6, n_out=8, num_experts=4, top_k=2,
                                     capacity_factor=2.0, activation="relu"))
                .layer(OutputLayer(n_in=8, n_out=4, activation="softmax",
                                   loss=LossFunction.MCXENT))
                .build())
        return MultiLayerNetwork(conf).init()

    net_a, net_b = make(), make()
    mesh = make_mesh(jax.devices()[:4], axes=(EXPERT_AXIS,))
    step, place = expert_parallel_step(net_a, mesh)
    place(net_a)
    rng = np.random.default_rng(15)
    f = jnp.asarray(rng.normal(size=(16, 6)), jnp.float32)
    l = jnp.asarray(np.eye(4, dtype=np.float32)[rng.integers(0, 4, 16)])
    it = jax.device_put(jnp.asarray(0, jnp.int32), replicated(mesh))
    key = jax.device_put(jax.random.PRNGKey(0), replicated(mesh))
    pa, _, _, loss_a = step(net_a.params, net_a.states, net_a.updater_state,
                            it, key, f, l, None, None)
    raw = jax.jit(net_b._raw_step(False))
    pb, _, _, loss_b = raw(net_b.params, net_b.states, net_b.updater_state,
                           jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
                           f, l, None, None)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(pa),
                    jax.tree_util.tree_leaves(pb)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-6)


def test_moe_inference_routes_exactly_despite_capacity():
    """Capacity dispatch is a TRAIN-step device: at train=False the layer
    routes exactly (dense combine), so output()/score()/rnn_time_step agree
    with each other regardless of batch shape — even at a capacity factor
    tiny enough to drop almost every training assignment."""
    impl_s, p = _moe_impl(capacity_factor=1e-6, top_k=2)
    impl_d, _ = _moe_impl(capacity_factor=0.0, top_k=2)
    rng = np.random.default_rng(15)
    x = jnp.asarray(rng.normal(size=(32, 6)), jnp.float32)
    y_inf, _ = impl_s.forward(p, {}, x)                  # train=False
    y_dense, _ = impl_d.forward(p, {}, x)
    np.testing.assert_allclose(np.asarray(y_inf), np.asarray(y_dense),
                               rtol=1e-5, atol=1e-6)
    y_train, _ = impl_s.forward(p, {}, x, train=True)    # drops ≫ 0
    assert float(np.max(np.abs(np.asarray(y_train)
                               - np.asarray(y_dense)))) > 1e-3


def test_moe_rejects_bad_routing_config():
    """top_k outside [1, num_experts] or negative capacity must raise at
    init, not produce NaN gates (review finding)."""
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.conf.layers import MoEDenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu import Sgd

    def build(**kw):
        conf = (NeuralNetConfiguration.builder().seed(1)
                .updater(Sgd(learning_rate=0.1)).activation("identity")
                .list()
                .layer(MoEDenseLayer(n_in=4, n_out=4, **kw))
                .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    with pytest.raises(ValueError, match="top_k"):
        build(num_experts=4, top_k=0)
    with pytest.raises(ValueError, match="top_k"):
        build(num_experts=4, top_k=5)
    with pytest.raises(ValueError, match="capacity_factor"):
        build(num_experts=4, top_k=2, capacity_factor=-1.0)


def test_moe_sparse_grouped_dispatch_matches_dense():
    """Multi-group dispatch (n > group_size, with a zero-padded tail group):
    ample capacity ⇒ parity with the dense oracle for EVERY token, including
    the tail group's real tokens."""
    impl_s, p = _moe_impl(capacity_factor=4.0)
    impl_s.conf.group_size = 16          # 3 full groups + 5-token tail
    impl_d, _ = _moe_impl(capacity_factor=0.0)
    rng = np.random.default_rng(13)
    x = jnp.asarray(rng.normal(size=(53, 6)), jnp.float32)
    ys, _ = impl_s.forward(p, {}, x, train=True)
    yd, _ = impl_d.forward(p, {}, x)
    assert ys.shape == yd.shape == (53, 8)
    np.testing.assert_allclose(np.asarray(ys), np.asarray(yd),
                               rtol=1e-4, atol=1e-5)


def test_moe_sparse_tail_padding_claims_no_capacity():
    """A nearly-empty tail group at TIGHT capacity must treat its real tokens
    exactly like a dedicated group of the same tokens would: padding rows
    claim no expert slots. (Regression: top_k on zero gates one-hots expert
    0..k-1, which would displace real assignments.)"""
    impl_s, p = _moe_impl(capacity_factor=1.0)
    impl_s.conf.group_size = 32
    rng = np.random.default_rng(17)
    x_main = jnp.asarray(rng.normal(size=(32, 6)), jnp.float32)
    x_tail = jnp.asarray(rng.normal(size=(3, 6)), jnp.float32)
    y_joint, _ = impl_s.forward(p, {}, jnp.concatenate([x_main, x_tail]),
                                train=True)
    y_tail, _ = impl_s.forward(p, {}, x_tail, train=True)
    # per-group capacity assignment ⇒ the tail group computed alone (its own
    # single group, 3 real tokens, no pads) must match the joint run's tail
    np.testing.assert_allclose(np.asarray(y_joint[32:]), np.asarray(y_tail),
                               rtol=1e-4, atol=1e-5)


def test_moe_sparse_dispatch_memory_linear_in_tokens():
    """The dispatch intermediates scale with n·G, not n²: jaxpr shapes for a
    2×-token run contain no tensor whose element count grew 4× (quadratic)."""
    import re

    def max_elems(n):
        impl_s, p = _moe_impl(capacity_factor=1.25)
        impl_s.conf.group_size = 64
        x = jnp.zeros((n, 6), jnp.float32)
        jaxpr = jax.make_jaxpr(
            lambda pp, xx: impl_s.forward(pp, {}, xx, train=True))(p, x)
        worst = 0
        for eqn in jaxpr.eqns:
            for v in eqn.outvars:
                shape = getattr(v.aval, "shape", ())
                worst = max(worst, int(np.prod(shape)) if shape else 0)
        return worst

    m1, m2 = max_elems(256), max_elems(512)
    assert m2 <= m1 * 2.5, (m1, m2)   # linear (2×), not quadratic (4×)
