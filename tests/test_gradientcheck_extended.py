"""Extended gradient-check breadth (VERDICT item 5) — mirrors the reference's
13-suite coverage in ``deeplearning4j-core/src/test/.../gradientcheck/``:
``LossFunctionGradientCheck``, ``VaeGradientCheckTests``,
``YoloGradientCheckTests``, ``GradientCheckTestsComputationGraph`` (merge /
elementwise / skip), masking variants, ``NoBiasGradientCheckTests``, frozen
layers, embedding, global pooling, bidirectional/Graves recurrent familes.

All checks run in f64 on the CPU backend (the reference's double-precision
rule, ``GradientCheckUtil.java:122``).
"""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                InputType, Sgd, DataSet)
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.conf.layers import (
    DenseLayer, OutputLayer, ConvolutionLayer, SubsamplingLayer,
    BatchNormalization, LSTM, GravesLSTM, GravesBidirectionalLSTM, SimpleRnn,
    Bidirectional, RnnOutputLayer, EmbeddingLayer, EmbeddingSequenceLayer,
    GlobalPoolingLayer, PoolingType, Yolo2OutputLayer, FrozenLayer, LossLayer,
    CenterLossOutputLayer, AutoEncoder, VariationalAutoencoder, ActivationLayer)
from deeplearning4j_tpu.nn.conf import (GaussianReconstructionDistribution,
                                        BernoulliReconstructionDistribution,
                                        CompositeReconstructionDistribution)
from deeplearning4j_tpu.nn.gradientcheck import (GradientCheckUtil,
                                                 check_function_gradients,
                                                 double_precision)
from deeplearning4j_tpu.nn.losses import LossFunction


def _f64_builder():
    return (NeuralNetConfiguration.builder()
            .seed(12345).updater(Sgd(learning_rate=1.0))
            .dtype("float64").compute_dtype("float64"))


def _onehot(rng, n, c):
    return np.eye(c)[rng.integers(0, c, n)].astype(np.float64)


def _check(net, ds, **kw):
    kw.setdefault("max_per_param", 12)
    kw.setdefault("print_results", True)
    assert GradientCheckUtil.check_gradients(net, ds, **kw)


# ------------------------------------------------- every loss function
# (activation, label factory) per loss — mirrors LossFunctionGradientCheck's
# valid-domain pairing table
def _labels_real(rng, n, c):
    return rng.normal(size=(n, c))


def _labels_pos(rng, n, c):
    return np.abs(rng.normal(size=(n, c))) + 0.5


def _labels_binary(rng, n, c):
    return (rng.random((n, c)) > 0.5).astype(np.float64)


def _labels_dist(rng, n, c):
    p = rng.random((n, c)) + 0.05
    return p / p.sum(axis=1, keepdims=True)


def _labels_pm1(rng, n, c):
    return np.sign(rng.normal(size=(n, c))) + (rng.normal(size=(n, c)) == 0)


_LOSS_CASES = [
    ("mse", "identity", _labels_real),
    ("mse", "tanh", _labels_real),
    ("l2", "identity", _labels_real),
    ("l1", "identity", _labels_real),
    ("mean_absolute_error", "identity", _labels_real),
    ("mean_absolute_percentage_error", "identity", _labels_pos),
    ("mean_squared_logarithmic_error", "softplus", _labels_pos),
    ("mcxent", "softmax", lambda rng, n, c: _onehot(rng, n, c)),
    ("negativeloglikelihood", "softmax", lambda rng, n, c: _onehot(rng, n, c)),
    ("xent", "sigmoid", _labels_binary),
    ("reconstruction_crossentropy", "sigmoid",
     lambda rng, n, c: rng.random((n, c)) * 0.9 + 0.05),
    ("kl_divergence", "softmax", _labels_dist),
    ("poisson", "softplus", lambda rng, n, c:
     rng.integers(0, 5, (n, c)).astype(np.float64)),
    ("cosine_proximity", "identity", _labels_real),
    ("squared_hinge", "identity", _labels_pm1),
]


@pytest.mark.parametrize("loss,act,labels", _LOSS_CASES,
                         ids=[f"{l}-{a}" for l, a, _ in _LOSS_CASES])
def test_loss_function_gradients(loss, act, labels):
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(DenseLayer(n_in=4, n_out=5))
                .layer(OutputLayer(n_in=5, n_out=3, activation=act, loss=loss))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(7)
        ds = DataSet(rng.normal(size=(5, 4)), labels(rng, 5, 3))
        _check(net, ds)


# MAE/L1/hinge are piecewise-linear (kinks make central differences unreliable
# exactly at them); the cases above use seeds that avoid the kinks, matching
# the reference's tolerance-tuned LossFunctionGradientCheck.


# ------------------------------------------------- no-bias nets
def test_no_bias_gradients():
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(DenseLayer(n_in=4, n_out=5, has_bias=False))
                .layer(OutputLayer(n_in=5, n_out=3, activation="softmax",
                                   loss="mcxent", has_bias=False))
                .build())
        net = MultiLayerNetwork(conf).init()
        assert not any("b" == k for lp in net.params.values() for k in lp)
        rng = np.random.default_rng(8)
        _check(net, DataSet(rng.normal(size=(6, 4)), _onehot(rng, 6, 3)))


# ------------------------------------------------- embedding (int inputs)
def test_embedding_gradients():
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(EmbeddingLayer(n_in=9, n_out=5))
                .layer(OutputLayer(n_in=5, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(9)
        f = rng.integers(0, 9, size=(6, 1)).astype(np.float64)
        _check(net, DataSet(f, _onehot(rng, 6, 3)))


# ------------------------------------------------- recurrent family + masking
@pytest.mark.parametrize("layer", [
    GravesLSTM(n_in=3, n_out=4, activation="tanh"),
    GravesBidirectionalLSTM(n_in=3, n_out=4, activation="tanh"),
    SimpleRnn(n_in=3, n_out=4, activation="tanh"),
    Bidirectional(inner=LSTM(n_in=3, n_out=4, activation="tanh")),
], ids=["graves", "graves-bidi", "simple", "bidi-wrapper"])
def test_recurrent_family_gradients(layer):
    with double_precision():
        # GravesBidirectionalLSTM sums directions (stays n_out);
        # Bidirectional(concat) doubles it
        n_out_rnn = 8 if isinstance(layer, Bidirectional) else 4
        conf = (_f64_builder()
                .list()
                .layer(layer)
                .layer(RnnOutputLayer(n_in=n_out_rnn, n_out=2,
                                      activation="softmax", loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(10)
        T = 4
        f = rng.normal(size=(3, T, 3))
        l = np.stack([_onehot(rng, T, 2) for _ in range(3)])
        _check(net, ds=DataSet(f, l), max_per_param=8)


def test_rnn_masking_gradients():
    """Per-example sequence masks flow through the loss (reference
    GradientCheckTests masking variants)."""
    with double_precision():
        conf = (_f64_builder()
                .list()
                .layer(LSTM(n_in=3, n_out=4, activation="tanh"))
                .layer(RnnOutputLayer(n_in=4, n_out=2, activation="softmax",
                                      loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(11)
        T = 5
        f = rng.normal(size=(4, T, 3))
        l = np.stack([_onehot(rng, T, 2) for _ in range(4)])
        lengths = np.array([5, 3, 4, 2])
        mask = (np.arange(T)[None, :] < lengths[:, None]).astype(np.float64)
        ds = DataSet(f, l, features_mask=mask, labels_mask=mask)
        _check(net, ds, max_per_param=8)


def test_global_pooling_rnn_masked_gradients():
    with double_precision():
        conf = (_f64_builder()
                .list()
                .layer(LSTM(n_in=3, n_out=4, activation="tanh"))
                .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
                .layer(OutputLayer(n_in=4, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(12)
        T = 4
        f = rng.normal(size=(3, T, 3))
        l = _onehot(rng, 3, 2)
        mask = (np.arange(T)[None, :] < np.array([4, 2, 3])[:, None]).astype(
            np.float64)
        _check(net, DataSet(f, l, features_mask=mask), max_per_param=8)


# ------------------------------------------------- frozen layers
def test_frozen_layer_gradients():
    """Frozen params: AD gradient exactly zero; the rest still checks out
    (reference FrozenLayer + gradient check pattern)."""
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(FrozenLayer(inner=DenseLayer(n_in=4, n_out=5)))
                .layer(DenseLayer(n_in=5, n_out=5))
                .layer(OutputLayer(n_in=5, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(13)
        ds = DataSet(rng.normal(size=(6, 4)), _onehot(rng, 6, 3))
        grads, _ = net.compute_gradient_and_score(ds)
        for k, v in grads["0"].items():
            assert float(jnp.abs(v).max()) == 0.0, f"frozen 0/{k} has gradient"
        _check(net, ds, exclude={"0/"})


# ------------------------------------------------- output layer variants
def test_loss_layer_and_activation_layer_gradients():
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(DenseLayer(n_in=4, n_out=3))
                .layer(ActivationLayer(activation="softmax"))
                .layer(LossLayer(loss="mcxent", activation="identity"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(14)
        _check(net, DataSet(rng.normal(size=(6, 4)), _onehot(rng, 6, 3)))


def test_center_loss_output_gradients():
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(DenseLayer(n_in=4, n_out=5))
                .layer(CenterLossOutputLayer(n_in=5, n_out=3,
                                             activation="softmax",
                                             loss="mcxent", lambda_=0.1))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(15)
        ds = DataSet(rng.normal(size=(6, 4)), _onehot(rng, 6, 3))
        # centers are state (EMA-updated outside AD), not checked params
        _check(net, ds)


# ------------------------------------------------- pretrain losses (VAE, AE)
@pytest.mark.parametrize("dist", [
    GaussianReconstructionDistribution(),
    BernoulliReconstructionDistribution(),
    (CompositeReconstructionDistribution.builder()
     .add_distribution(3, GaussianReconstructionDistribution())
     .add_distribution(3, BernoulliReconstructionDistribution()).build()),
], ids=["gaussian", "bernoulli", "composite"])
def test_vae_pretrain_gradients(dist):
    """Reference VaeGradientCheckTests (pretrain path)."""
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(VariationalAutoencoder(
                    n_in=6, n_out=3, encoder_layer_sizes=(7,),
                    decoder_layer_sizes=(7,),
                    reconstruction_distribution=dist, num_samples=1))
                .layer(OutputLayer(n_in=3, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(16)
        x = rng.normal(size=(5, 6))
        if isinstance(dist, BernoulliReconstructionDistribution):
            x = (x > 0).astype(np.float64)
        impl = net.impls[0]
        key = jax.random.PRNGKey(0)
        assert check_function_gradients(
            lambda p: impl.pretrain_loss(p, jnp.asarray(x), key),
            net.params["0"], max_per_param=10)


def test_vae_supervised_gradients():
    """Reference VaeGradientCheckTests (supervised/backprop path — VAE used
    mid-network emits mean of q(z|x))."""
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(VariationalAutoencoder(
                    n_in=6, n_out=3, encoder_layer_sizes=(7,),
                    decoder_layer_sizes=(7,)))
                .layer(OutputLayer(n_in=3, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(17)
        ds = DataSet(rng.normal(size=(6, 6)), _onehot(rng, 6, 2))
        # decoder params don't participate in the supervised path
        _check(net, ds, exclude={"0/d", "0/x"})


def test_autoencoder_pretrain_gradients():
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(AutoEncoder(n_in=5, n_out=3, corruption_level=0.0))
                .layer(OutputLayer(n_in=3, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(18)
        x = rng.normal(size=(5, 5))
        impl = net.impls[0]
        key = jax.random.PRNGKey(0)
        assert check_function_gradients(
            lambda p: impl.pretrain_loss(p, jnp.asarray(x), key),
            net.params["0"], max_per_param=10)


# ------------------------------------------------- YOLO2
def test_yolo2_gradients():
    """Reference YoloGradientCheckTests."""
    with double_precision():
        gh = gw = 3
        C = 2
        B = 2
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(ConvolutionLayer(n_out=B * 5 + C, kernel_size=(1, 1),
                                        stride=(1, 1)))
                .layer(Yolo2OutputLayer(boxes=[[1.0, 1.0], [2.0, 2.0]]))
                .set_input_type(InputType.convolutional(gh, gw, 4))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(19)
        f = rng.normal(size=(2, 4, gh, gw))
        # labels [b, 4+C, gh, gw]: one object per image
        labels = np.zeros((2, 4 + C, gh, gw))
        for b in range(2):
            i, j = rng.integers(0, gh), rng.integers(0, gw)
            labels[b, :4, i, j] = [j + 0.2, i + 0.2, j + 0.8, i + 0.8]
            labels[b, 4 + rng.integers(0, C), i, j] = 1.0
        _check(net, DataSet(f, labels), max_per_param=10,
               max_rel_error=5e-3)


# ------------------------------------------------- ComputationGraph topologies
def _cg(conf_builder):
    return ComputationGraph(conf_builder.build()).init()


def test_cg_merge_vertex_gradients():
    """Reference GradientCheckTestsComputationGraph merge topology."""
    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .graph_builder()
                .add_inputs("in")
                .add_layer("a", DenseLayer(n_in=4, n_out=3), "in")
                .add_layer("b", DenseLayer(n_in=4, n_out=3), "in")
                .add_layer("out", OutputLayer(n_in=6, n_out=2,
                                              activation="softmax",
                                              loss="mcxent"), "a", "b")
                .set_outputs("out"))
        net = _cg(conf)
        rng = np.random.default_rng(20)
        ds = DataSet(rng.normal(size=(5, 4)), _onehot(rng, 5, 2))
        _check(net, ds)


def test_cg_elementwise_and_skip_gradients():
    """Elementwise-add vertex + skip connection (residual pattern)."""
    with double_precision():
        from deeplearning4j_tpu.nn.conf.graph import ElementWiseVertex
        conf = (_f64_builder().activation("tanh")
                .graph_builder()
                .add_inputs("in")
                .add_layer("d1", DenseLayer(n_in=4, n_out=4), "in")
                .add_vertex("add", ElementWiseVertex("add"), "d1", "in")
                .add_layer("out", OutputLayer(n_in=4, n_out=2,
                                              activation="softmax",
                                              loss="mcxent"), "add")
                .set_outputs("out"))
        net = _cg(conf)
        rng = np.random.default_rng(21)
        ds = DataSet(rng.normal(size=(5, 4)), _onehot(rng, 5, 2))
        _check(net, ds)


def test_cg_multi_output_gradients():
    """Two output layers training jointly (multi-task)."""
    with double_precision():
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        conf = (_f64_builder().activation("tanh")
                .graph_builder()
                .add_inputs("in")
                .add_layer("trunk", DenseLayer(n_in=4, n_out=6), "in")
                .add_layer("out1", OutputLayer(n_in=6, n_out=2,
                                               activation="softmax",
                                               loss="mcxent"), "trunk")
                .add_layer("out2", OutputLayer(n_in=6, n_out=3,
                                               activation="identity",
                                               loss="mse"), "trunk")
                .set_outputs("out1", "out2"))
        net = _cg(conf)
        rng = np.random.default_rng(22)
        mds = MultiDataSet([rng.normal(size=(5, 4))],
                           [_onehot(rng, 5, 2), rng.normal(size=(5, 3))])
        _check(net, mds)


def test_layer_norm_gradients():
    """LayerNormalization (net-new: transformer family) — f64 numeric vs
    analytic gradients through LN on both [b, F] and sequence [b, T, F]
    activations."""
    from deeplearning4j_tpu.nn.conf.layers import LayerNormalization

    with double_precision():
        conf = (_f64_builder().activation("tanh")
                .list()
                .layer(DenseLayer(n_in=4, n_out=6))
                .layer(LayerNormalization(n_in=6, n_out=6))
                .layer(OutputLayer(n_in=6, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        rng = np.random.default_rng(3)
        f = rng.normal(size=(5, 4)) * 3.0 + 1.0
        _check(net, DataSet(f, _onehot(rng, 5, 3)))

        seq = (_f64_builder().activation("tanh")
               .list()
               .layer(SimpleRnn(n_in=3, n_out=6, activation="tanh"))
               .layer(LayerNormalization(n_in=6, n_out=6))
               .layer(RnnOutputLayer(n_in=6, n_out=2, activation="softmax",
                                     loss="mcxent"))
               .build())
        net2 = MultiLayerNetwork(seq).init()
        f2 = rng.normal(size=(4, 5, 3))
        l2 = np.eye(2, dtype=np.float64)[rng.integers(0, 2, (4, 5))]
        _check(net2, DataSet(f2, l2))
