"""MultiLayerNetwork end-to-end behavior tests (reference test analog:
``deeplearning4j-core/src/test/java/org/deeplearning4j/nn/multilayer/``)."""
import numpy as np
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                InputType, DataSet, ListDataSetIterator, Adam, Sgd,
                                WeightInit, BackpropType)
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, OutputLayer,
                                               ConvolutionLayer, SubsamplingLayer,
                                               BatchNormalization, LSTM,
                                               GravesLSTM, RnnOutputLayer,
                                               DropoutLayer, GlobalPoolingLayer,
                                               EmbeddingSequenceLayer, PoolingType)


def _toy_classification(n=256, d=10, classes=3, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, d)).astype(np.float32)
    w = rng.standard_normal((d, classes))
    y = np.argmax(x @ w + 0.1 * rng.standard_normal((n, classes)), axis=1)
    labels = np.eye(classes, dtype=np.float32)[y]
    return x, labels


class TestMLP:
    def test_fit_reduces_score_and_learns(self):
        x, labels = _toy_classification()
        conf = (NeuralNetConfiguration.builder()
                .seed(7).updater(Adam(learning_rate=1e-2))
                .list()
                .layer(DenseLayer(n_in=10, n_out=32, activation="relu"))
                .layer(OutputLayer(n_in=32, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, labels)
        initial = net.score(ds)
        net.fit(ListDataSetIterator([ds], batch_size=64), epochs=30)
        final = net.score(ds)
        assert final < initial * 0.5
        ev = net.evaluate(ListDataSetIterator([ds]))
        assert ev.accuracy() > 0.85

    def test_output_shape_and_softmax(self):
        x, labels = _toy_classification(n=8)
        conf = (NeuralNetConfiguration.builder().list()
                .layer(DenseLayer(n_in=10, n_out=16))
                .layer(OutputLayer(n_in=16, n_out=3, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = np.asarray(net.output(x))
        assert out.shape == (8, 3)
        assert np.allclose(out.sum(axis=1), 1.0, atol=1e-5)

    def test_params_flat_roundtrip(self):
        conf = (NeuralNetConfiguration.builder().seed(1).list()
                .layer(DenseLayer(n_in=4, n_out=5))
                .layer(OutputLayer(n_in=5, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        vec = net.params_flat()
        assert vec.size == net.num_params() == (4 * 5 + 5) + (5 * 2 + 2)
        x = np.random.default_rng(0).standard_normal((3, 4)).astype(np.float32)
        out1 = np.asarray(net.output(x))
        net2 = MultiLayerNetwork(conf).init()
        net2.set_params_flat(vec)
        out2 = np.asarray(net2.output(x))
        assert np.allclose(out1, out2, atol=1e-6)

    def test_l2_increases_score(self):
        x, labels = _toy_classification(n=32)
        base = (NeuralNetConfiguration.builder().seed(3).list()
                .layer(DenseLayer(n_in=10, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=3, activation="softmax"))
                .build())
        reg = (NeuralNetConfiguration.builder().seed(3).l2(0.1).list()
               .layer(DenseLayer(n_in=10, n_out=8))
               .layer(OutputLayer(n_in=8, n_out=3, activation="softmax"))
               .build())
        n1 = MultiLayerNetwork(base).init()
        n2 = MultiLayerNetwork(reg).init()
        ds = DataSet(x, labels)
        assert n2.score(ds) > n1.score(ds)

    def test_frozen_global_config_defaults(self):
        conf = (NeuralNetConfiguration.builder()
                .activation("tanh").weight_init(WeightInit.ZERO).list()
                .layer(DenseLayer(n_in=3, n_out=4))
                .layer(OutputLayer(n_in=4, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        # zero weights + tanh -> dense output all zeros
        out = net.feed_forward(np.ones((2, 3), np.float32))
        assert np.allclose(out[1], 0.0)


class TestCNN:
    def test_lenet_mini_trains(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal((64, 1, 12, 12)).astype(np.float32)
        y = (x.mean(axis=(1, 2, 3)) > 0).astype(int)
        labels = np.eye(2, dtype=np.float32)[y]
        conf = (NeuralNetConfiguration.builder()
                .seed(11).updater(Adam(learning_rate=3e-3))
                .list()
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=8,
                                        activation="relu"))
                .layer(SubsamplingLayer(kernel_size=(2, 2), stride=(2, 2)))
                .layer(BatchNormalization())
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.convolutional(12, 12, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, labels)
        s0 = net.score(ds)
        net.fit(ListDataSetIterator([ds], batch_size=32), epochs=20)
        assert net.score(ds) < s0
        ev = net.evaluate(ListDataSetIterator([ds]))
        assert ev.accuracy() > 0.8

    def test_bn_state_updates_in_training(self):
        x = np.random.default_rng(0).standard_normal((16, 1, 6, 6)).astype(np.float32) * 3 + 1
        labels = np.eye(2, dtype=np.float32)[np.zeros(16, int)]
        conf = (NeuralNetConfiguration.builder().list()
                .layer(ConvolutionLayer(kernel_size=(3, 3), n_out=4))
                .layer(BatchNormalization())
                .layer(OutputLayer(n_out=2, activation="softmax"))
                .set_input_type(InputType.convolutional(6, 6, 1))
                .build())
        net = MultiLayerNetwork(conf).init()
        mean_before = np.asarray(net.states["1"]["mean"]).copy()
        net.fit(DataSet(x, labels))
        mean_after = np.asarray(net.states["1"]["mean"])
        assert not np.allclose(mean_before, mean_after)


class TestRNN:
    def _seq_data(self, n=64, t=12, d=4, seed=0):
        # predict sign of running mean of feature 0, per timestep
        rng = np.random.default_rng(seed)
        x = rng.standard_normal((n, t, d)).astype(np.float32)
        cum = np.cumsum(x[:, :, 0], axis=1) / np.arange(1, t + 1)
        y = (cum > 0).astype(int)
        labels = np.eye(2, dtype=np.float32)[y]
        return x, labels

    def test_lstm_trains(self):
        x, labels = self._seq_data()
        conf = (NeuralNetConfiguration.builder()
                .seed(5).updater(Adam(learning_rate=1e-2))
                .list()
                .layer(LSTM(n_in=4, n_out=16, activation="tanh"))
                .layer(RnnOutputLayer(n_in=16, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, labels)
        s0 = net.score(ds)
        net.fit(ListDataSetIterator([ds], batch_size=32), epochs=25)
        assert net.score(ds) < s0 * 0.9
        out = np.asarray(net.output(x))
        assert out.shape == (64, 12, 2)

    def test_graves_lstm_has_peepholes(self):
        conf = (NeuralNetConfiguration.builder().list()
                .layer(GravesLSTM(n_in=3, n_out=5))
                .layer(RnnOutputLayer(n_in=5, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        assert "pi" in net.params["0"]
        assert net.params["0"]["W"].shape == (3, 20)

    def test_masking_changes_loss(self):
        x, labels = self._seq_data(n=8)
        mask = np.ones((8, 12), np.float32)
        mask[:, 6:] = 0
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(LSTM(n_in=4, n_out=8))
                .layer(RnnOutputLayer(n_in=8, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        full = net.score(DataSet(x, labels))
        masked = net.score(DataSet(x, labels, features_mask=mask, labels_mask=mask))
        assert masked < full  # half the timesteps contribute

    def test_rnn_time_step_matches_full_forward(self):
        x, _ = self._seq_data(n=4, t=6)
        conf = (NeuralNetConfiguration.builder().seed(5).list()
                .layer(LSTM(n_in=4, n_out=8))
                .layer(RnnOutputLayer(n_in=8, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        full = np.asarray(net.output(x))
        net.rnn_clear_previous_state()
        steps = []
        for t in range(6):
            steps.append(np.asarray(net.rnn_time_step(x[:, t, :])))
        stepwise = np.stack(steps, axis=1)
        assert np.allclose(full, stepwise, atol=1e-4)

    def test_tbptt_runs(self):
        x, labels = self._seq_data(n=16, t=20)
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Adam(learning_rate=1e-2)).list()
                .layer(LSTM(n_in=4, n_out=8))
                .layer(RnnOutputLayer(n_in=8, n_out=2, activation="softmax"))
                .backprop_type(BackpropType.TruncatedBPTT)
                .t_bptt_forward_length(5)
                .t_bptt_backward_length(5)
                .build())
        net = MultiLayerNetwork(conf).init()
        ds = DataSet(x, labels)
        s0 = net.score(ds)
        net.fit(ListDataSetIterator([ds]), epochs=10)
        assert net.score(ds) < s0

    def test_global_pooling_classifier(self):
        x, labels_seq = self._seq_data(n=32, t=10)
        labels = labels_seq[:, -1, :]  # sequence-level label
        conf = (NeuralNetConfiguration.builder().seed(2)
                .updater(Adam(learning_rate=1e-2)).list()
                .layer(LSTM(n_in=4, n_out=8))
                .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = np.asarray(net.output(x))
        assert out.shape == (32, 2)
        net.fit(DataSet(x, labels))


class TestEmbedding:
    def test_embedding_sequence(self):
        rng = np.random.default_rng(0)
        tokens = rng.integers(0, 20, size=(16, 8))
        labels = np.eye(2, dtype=np.float32)[(tokens.sum(axis=1) % 2)]
        conf = (NeuralNetConfiguration.builder().updater(Adam(learning_rate=1e-2))
                .list()
                .layer(EmbeddingSequenceLayer(n_in=20, n_out=6))
                .layer(GlobalPoolingLayer(pooling_type=PoolingType.AVG))
                .layer(OutputLayer(n_in=6, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        out = np.asarray(net.output(tokens))
        assert out.shape == (16, 2)
        net.fit(DataSet(tokens, labels))


class TestDropout:
    def test_dropout_only_in_training(self):
        x = np.ones((4, 10), np.float32)
        conf = (NeuralNetConfiguration.builder().seed(0).list()
                .layer(DropoutLayer(dropout=0.5))
                .layer(OutputLayer(n_in=10, n_out=2, activation="softmax"))
                .build())
        net = MultiLayerNetwork(conf).init()
        # inference: dropout inactive -> deterministic
        o1 = np.asarray(net.output(x))
        o2 = np.asarray(net.output(x))
        assert np.allclose(o1, o2)


def test_iterations_config_scanned_equals_sequential():
    """0.9.x ``Builder.iterations(n)``: n optimizer steps per minibatch,
    compiled as ONE lax.scan program — must match n sequential fits exactly
    (dropout-free net, same seed)."""
    import jax
    import numpy as np
    from deeplearning4j_tpu import (NeuralNetConfiguration,
                                    MultiLayerNetwork, DataSet, Sgd)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer

    def build(n_iter):
        conf = (NeuralNetConfiguration.builder().seed(5)
                .updater(Sgd(learning_rate=0.1)).activation("tanh")
                .iterations(n_iter)
                .list()
                .layer(DenseLayer(n_in=4, n_out=6))
                .layer(OutputLayer(n_in=6, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                 np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])
    net_scan = build(3)
    net_seq = build(1)
    net_scan.fit(ds)
    for _ in range(3):
        net_seq.fit(ds)
    assert net_scan.iteration_count == 3 == net_seq.iteration_count
    for a, b in zip(jax.tree_util.tree_leaves(net_scan.params),
                    jax.tree_util.tree_leaves(net_seq.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_iterations_config_tbptt_scanned():
    """iterations(n) on the TBPTT path: n optimizer steps per segment inside
    one scanned program, equal to the sequential-iteration semantics."""
    import numpy as np
    from deeplearning4j_tpu import (NeuralNetConfiguration,
                                    MultiLayerNetwork, DataSet, Sgd)
    from deeplearning4j_tpu.nn.conf import BackpropType
    from deeplearning4j_tpu.nn.conf.layers import SimpleRnn, RnnOutputLayer

    def build(n_iter):
        conf = (NeuralNetConfiguration.builder().seed(4)
                .updater(Sgd(learning_rate=0.05)).activation("tanh")
                .iterations(n_iter)
                .list()
                .layer(SimpleRnn(n_in=3, n_out=5))
                .layer(RnnOutputLayer(n_in=5, n_out=3, activation="softmax",
                                      loss="mcxent"))
                .build())
        conf.backprop_type = BackpropType.TruncatedBPTT
        conf.tbptt_fwd_length = conf.tbptt_back_length = 4
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(1)
    f = rng.normal(size=(2, 8, 3)).astype(np.float32)
    l = np.eye(3, dtype=np.float32)[rng.integers(0, 3, (2, 8))].astype(
        np.float32)
    ds = DataSet(f, l)
    net = build(2)
    net.fit(ds)
    # 2 segments x 2 iterations
    assert net.iteration_count == 4
    assert np.isfinite(float(net.score_))


def test_tbptt_fused_scan_matches_per_segment_loop():
    """The fused lax.scan TBPTT path (one dispatch per batch) must produce
    the same params as dispatching each segment separately (round-4 LSTM
    dispatch-latency lever; math identical, only the launch granularity
    changes)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                    DataSet, Sgd)
    from deeplearning4j_tpu.nn.conf.layers import LSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.conf import BackpropType

    def make():
        conf = (NeuralNetConfiguration.builder().seed(41)
                .updater(Sgd(learning_rate=1e-2)).list()
                .backprop_type(BackpropType.TruncatedBPTT)
                .t_bptt_forward_length(4).t_bptt_backward_length(4)
                .layer(LSTM(n_in=3, n_out=8, activation="tanh"))
                .layer(RnnOutputLayer(n_in=8, n_out=2, activation="softmax",
                                      loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(43)
    T = 12  # 3 equal segments -> fused path
    f = rng.normal(size=(6, T, 3)).astype(np.float32)
    l = np.eye(2, dtype=np.float32)[rng.integers(0, 2, (6, T))].astype(
        np.float32)
    m = (np.arange(T)[None, :] < rng.integers(6, T + 1, (6, 1))).astype(
        np.float32)

    fused = make()
    fused._fit_batch(DataSet(f, l, features_mask=m, labels_mask=m))
    assert fused.iteration_count == 3

    manual = make()
    step = manual._ensure_tbptt_step()
    rnn = manual._init_rnn_state(6)
    fj, lj, mj = jnp.asarray(f), jnp.asarray(l), jnp.asarray(m)
    for s in range(3):
        sl = slice(4 * s, 4 * (s + 1))
        (manual.params, manual.states, manual.updater_state, loss,
         rnn) = step(manual.params, manual.states, manual.updater_state,
                     jnp.asarray(s, jnp.int32), manual._next_rng(),
                     fj[:, sl], lj[:, sl], mj[:, sl], mj[:, sl], rnn)

    for k in manual.params:
        for p in manual.params[k]:
            np.testing.assert_allclose(np.asarray(fused.params[k][p]),
                                       np.asarray(manual.params[k][p]),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=f"{k}/{p}")
