"""ModelSerializer round-trip tests (reference test pattern: SURVEY.md §4 item 3
serialization regression tests; format from ``util/ModelSerializer.java:37-41``)."""
import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                ComputationGraph, InputType, Adam, DataSet,
                                ModelSerializer, NormalizerStandardize)
from deeplearning4j_tpu.nn.conf.layers import (DenseLayer, OutputLayer,
                                               ConvolutionLayer, SubsamplingLayer,
                                               PoolingType)
from deeplearning4j_tpu.nn.losses import LossFunction


def _mln():
    conf = (NeuralNetConfiguration.builder()
            .seed(7).updater(Adam(learning_rate=1e-3)).activation("relu")
            .list()
            .layer(DenseLayer(n_in=8, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss=LossFunction.MCXENT))
            .build())
    return MultiLayerNetwork(conf).init()


def _ds(n=16, nin=8, nout=3, seed=0):
    rng = np.random.default_rng(seed)
    f = rng.normal(size=(n, nin)).astype(np.float32)
    l = np.eye(nout, dtype=np.float32)[rng.integers(0, nout, n)]
    return DataSet(f, l)


def test_mln_roundtrip_exact_resume(tmp_path):
    net = _mln()
    ds = _ds()
    net.fit(ds)  # builds updater state (Adam moments)
    path = str(tmp_path / "model.bin")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore_multi_layer_network(path)

    # params identical
    for k in net.params:
        for p in net.params[k]:
            np.testing.assert_array_equal(np.asarray(net.params[k][p]),
                                          np.asarray(restored.params[k][p]))
    assert restored.iteration_count == net.iteration_count

    # exact resume: one more step on each must produce identical params
    ds2 = _ds(seed=1)
    net.fit(ds2)
    restored.fit(ds2)
    for k in net.params:
        for p in net.params[k]:
            np.testing.assert_allclose(np.asarray(net.params[k][p]),
                                       np.asarray(restored.params[k][p]),
                                       rtol=1e-6)


def test_mln_outputs_match_after_restore(tmp_path):
    net = _mln()
    ds = _ds()
    net.fit(ds)
    path = str(tmp_path / "model.bin")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore_model(path)
    x = _ds(seed=3).features
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(restored.output(x)), rtol=1e-6)


def test_cg_roundtrip(tmp_path):
    conf = (NeuralNetConfiguration.builder()
            .seed(11).updater(Adam(learning_rate=1e-3))
            .graph_builder()
            .add_inputs("in")
            .add_layer("d", DenseLayer(n_in=8, n_out=8, activation="tanh"), "in")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                          loss=LossFunction.MCXENT), "d")
            .set_outputs("out")
            .build())
    net = ComputationGraph(conf).init()
    ds = _ds()
    net.fit(ds)
    path = str(tmp_path / "cg.bin")
    ModelSerializer.write_model(net, path)
    restored = ModelSerializer.restore_computation_graph(path)
    x = _ds(seed=4).features
    np.testing.assert_allclose(np.asarray(net.output(x)),
                               np.asarray(restored.output(x)), rtol=1e-6)


def test_wrong_type_raises(tmp_path):
    net = _mln()
    path = str(tmp_path / "model.bin")
    ModelSerializer.write_model(net, path)
    with pytest.raises(ValueError):
        ModelSerializer.restore_computation_graph(path)


def test_normalizer_roundtrip(tmp_path):
    net = _mln()
    ds = _ds()
    norm = NormalizerStandardize().fit(ds)
    path = str(tmp_path / "model.bin")
    ModelSerializer.write_model(net, path, normalizer=norm)
    restored_norm = ModelSerializer.restore_normalizer(path)
    np.testing.assert_allclose(norm.mean, restored_norm.mean)
    np.testing.assert_allclose(norm.std, restored_norm.std)
    ds2 = _ds(seed=9)
    a = norm._apply(ds2.features.copy())
    b = restored_norm._apply(ds2.features.copy())
    np.testing.assert_allclose(a, b)


def test_normalizer_time_series_per_feature():
    # stats are per feature, independent of sequence length (review finding)
    rng = np.random.default_rng(0)
    f10 = rng.normal(loc=3.0, size=(32, 10, 8)).astype(np.float32)
    norm = NormalizerStandardize().fit(DataSet(f10, None))
    assert norm.mean.shape == (8,)
    f5 = rng.normal(size=(32, 5, 8)).astype(np.float32)  # different seq length
    out = norm._apply(f5)
    assert out.shape == f5.shape
    # round trip
    np.testing.assert_allclose(norm._invert(out), f5, rtol=1e-4, atol=1e-4)


def test_model_guesser_sniffs_all_formats(tmp_path):
    """ModelGuesser (reference core util/ModelGuesser.java): one entry loads
    a DL4J zip, a Keras h5, or a bare config JSON without being told which."""
    import numpy as np
    from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                    Sgd)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.utils.model_serializer import ModelSerializer
    from deeplearning4j_tpu.utils.model_guesser import ModelGuesser

    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(Sgd(learning_rate=0.1)).activation("tanh").list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()

    # 1) DL4J zip
    zp = str(tmp_path / "m.zip")
    ModelSerializer.write_model(net, zp, save_updater=True)
    loaded = ModelGuesser.load_model_guess(zp)
    np.testing.assert_array_equal(np.asarray(loaded.params["0"]["W"]),
                                  np.asarray(net.params["0"]["W"]))

    # 2) bare config JSON → fresh net of the right container type
    jp = str(tmp_path / "conf.json")
    open(jp, "w").write(conf.to_json())
    fresh = ModelGuesser.load_model_guess(jp)
    assert type(fresh).__name__ == "MultiLayerNetwork"
    assert ModelGuesser.load_config_guess(jp).layers[0].n_out == 8

    # 3) Keras h5 (reuses a committed golden fixture)
    import os
    fixture = os.path.join(os.path.dirname(__file__), "resources", "keras",
                           "functional_inception.h5")
    if os.path.exists(fixture):
        km = ModelGuesser.load_model_guess(fixture)
        assert type(km).__name__ == "ComputationGraph"

    # junk JSON rejects with both parse errors listed
    bad = str(tmp_path / "bad.json")
    open(bad, "w").write("{\"neither\": true}")
    import pytest as _p
    with _p.raises(ValueError, match="either container"):
        ModelGuesser.load_config_guess(bad)
