"""Solver dispatch + LBFGS/CG/LineGD tests (reference ``optimize/solvers``
family: Solver.java dispatch, BackTrackLineSearchTest, LBFGS behavior)."""
import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                Sgd, DataSet, OptimizationAlgorithm)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.optimize.solvers import (Solver, LBFGS,
                                                 ConjugateGradient,
                                                 LineGradientDescent,
                                                 BackTrackLineSearch)


def _net(algo):
    conf = (NeuralNetConfiguration.builder().seed(5)
            .updater(Sgd(learning_rate=0.1)).activation("tanh")
            .optimization_algo(algo)
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _ds(seed=0):
    rng = np.random.default_rng(seed)
    f = rng.normal(size=(32, 4)).astype(np.float32)
    l = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 32)]
    return DataSet(f, l)


def test_backtrack_line_search_armijo():
    f = lambda x: float((x ** 2).sum())
    x = np.array([2.0, -3.0])
    g = 2 * x
    step, fnew = BackTrackLineSearch().search(f, x, f(x), g, -g)
    assert step > 0
    assert fnew < f(x)


@pytest.mark.parametrize("algo,cls", [
    (OptimizationAlgorithm.LBFGS, LBFGS),
    (OptimizationAlgorithm.CONJUGATE_GRADIENT, ConjugateGradient),
    (OptimizationAlgorithm.LINE_GRADIENT_DESCENT, LineGradientDescent),
])
def test_full_batch_optimizers_reduce_loss(algo, cls):
    net = _net(algo)
    ds = _ds()
    s0 = net.score(ds, training=True)
    solver = Solver.builder().model(net).max_iterations(30).build()
    assert solver.optimize(ds)
    s1 = net.score(ds, training=True)
    assert s1 < s0 * 0.9, (algo, s0, s1)


def test_lbfgs_beats_few_sgd_steps():
    # LBFGS full batch should reach a lower loss than 30 SGD steps (classic
    # small-problem behavior the reference's LBFGS exists for)
    ds = _ds(seed=3)
    sgd_net = _net(OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT)
    for _ in range(30):
        sgd_net.fit(ds)
    lbfgs_net = _net(OptimizationAlgorithm.LBFGS)
    Solver.builder().model(lbfgs_net).max_iterations(30).build().optimize(ds)
    assert lbfgs_net.score(ds, training=True) < sgd_net.score(ds, training=True)


def test_solver_sgd_dispatch():
    net = _net(OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT)
    ds = _ds()
    s0 = net.score(ds)
    Solver.builder().model(net).build().optimize(ds)
    assert net.score(ds) < s0


def test_param_and_gradient_iteration_listener(tmp_path):
    """Reference ParamAndGradientIterationListener: per-iteration param +
    update stats, collected rows and tab-delimited file output."""
    import os
    from deeplearning4j_tpu import (NeuralNetConfiguration,
                                    MultiLayerNetwork, DataSet, Sgd)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.listeners import (
        ParamAndGradientIterationListener)

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Sgd(learning_rate=0.1)).activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=6))
            .layer(OutputLayer(n_in=6, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    path = os.path.join(str(tmp_path), "stats.tsv")
    lst = ParamAndGradientIterationListener(output_to_console=False,
                                            file_path=path)
    net.set_listeners(lst)
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                 np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)])
    for _ in range(3):
        net.fit(ds)
    assert len(lst.rows) == 3
    # update stats are nonzero once training moves params
    assert abs(lst.rows[1][-1]) > 0  # updateMeanAbsValue
    lines = open(path).read().strip().splitlines()
    assert lines[0].startswith("iteration\tscore\tparamMean")
    assert len(lines) == 4  # header + 3 rows


def test_checkpoint_listener_rotation_and_exact_resume(tmp_path):
    """CheckpointListener saves every N iterations with keep-last rotation;
    the newest checkpoint restores an EXACT-resume model (params + updater
    state) that continues training identically to the uninterrupted run."""
    import os
    import jax
    from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                    DataSet, Adam)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener

    def make():
        conf = (NeuralNetConfiguration.builder().seed(17)
                .updater(Adam(learning_rate=1e-2)).activation("tanh")
                .list()
                .layer(DenseLayer(n_in=6, n_out=12))
                .layer(OutputLayer(n_in=12, n_out=3, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(19)
    f = rng.normal(size=(16, 6)).astype(np.float32)
    l = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)]
    ds = DataSet(f, l)

    ckdir = str(tmp_path / "ckpts")
    net = make()
    cl = CheckpointListener(ckdir, save_every_n_iterations=2,
                            save_every_n_epochs=0, keep_last=2)
    net.set_listeners(cl)
    for _ in range(8):                      # 8 iterations → 4 saves, keep 2
        net.fit(ds)
    files = CheckpointListener.checkpoints(ckdir)
    assert len(files) == 2                  # rotation pruned the older two
    assert files[-1].endswith("iter-8.zip")
    assert not any(p.endswith(".tmp") for p in os.listdir(ckdir))

    # exact resume: restored net + 2 more steps == uninterrupted 10 steps
    resumed = CheckpointListener.last_checkpoint(ckdir)
    for _ in range(2):
        resumed.fit(ds)

    reference = make()
    for _ in range(10):
        reference.fit(ds)
    for a, b in zip(jax.tree_util.tree_leaves(resumed.params),
                    jax.tree_util.tree_leaves(reference.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def test_checkpoint_listener_adopts_existing_directory(tmp_path):
    """A fresh listener attached to a directory with pre-crash checkpoints
    must continue the file index (newest stays newest) and rotate the old
    files out (review finding: per-instance counter restarted at 0)."""
    import os
    from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                    DataSet, Adam)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.optimize.listeners import CheckpointListener

    def make():
        conf = (NeuralNetConfiguration.builder().seed(23)
                .updater(Adam(learning_rate=1e-2)).activation("tanh")
                .list()
                .layer(DenseLayer(n_in=4, n_out=8))
                .layer(OutputLayer(n_in=8, n_out=2, activation="softmax",
                                   loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    rng = np.random.default_rng(29)
    ds = DataSet(rng.normal(size=(8, 4)).astype(np.float32),
                 np.eye(2, dtype=np.float32)[rng.integers(0, 2, 8)])
    d = str(tmp_path / "ck")

    net = make()
    net.set_listeners(CheckpointListener(d, save_every_n_iterations=1,
                                         save_every_n_epochs=0, keep_last=2))
    for _ in range(3):
        net.fit(ds)                                    # files 00002, 00003

    resumed = CheckpointListener.last_checkpoint(d)
    cl2 = CheckpointListener(d, save_every_n_iterations=1,
                             save_every_n_epochs=0, keep_last=2)
    resumed.set_listeners(cl2)
    resumed.fit(ds)                                    # must be file 00004
    files = [os.path.basename(p)
             for p in CheckpointListener.checkpoints(d)]
    assert files[-1].startswith("checkpoint-00004-"), files
    assert len(files) == 2                             # old ones rotated out
    again = CheckpointListener.last_checkpoint(d)
    assert again.iteration_count == 4
