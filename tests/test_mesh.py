"""Unified 2-D mesh substrate (parallel/mesh.py — docs/PARALLELISM.md
"Unified mesh substrate"): MeshSpec auto-factorization + validation, the
composed DP×TP step, ZeRO riding the data axis of any mesh (pinned
bit-exact vs replicated), the closed jit-signature set, and the /profile
mesh block. Runs on the conftest 8-device virtual CPU mesh."""
import numpy as np
import pytest
import jax

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                Adam, DataSet, ListDataSetIterator)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.parallel import (ParallelWrapper, TrainingMode,
                                         MeshSpec, make_mesh, mesh_block,
                                         require_axes, zero_update_specs,
                                         tensor_parallel_step,
                                         DATA_AXIS, MODEL_AXIS)
from deeplearning4j_tpu.parallel.mesh import auto_factor, reset_mesh_registry


def _adam_net(seed=7):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(learning_rate=1e-2)).activation("tanh")
            .list()
            .layer(DenseLayer(n_in=6, n_out=16))
            .layer(DenseLayer(n_in=16, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=4, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n=4, size=32, seed=0):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.normal(size=(size, 6)).astype(np.float32),
                    np.eye(4, dtype=np.float32)[rng.integers(0, 4, size)])
            for _ in range(n)]


def _fit(net, epochs=3, batches=None, **builder_kw):
    b = ParallelWrapper.Builder(net)
    for name, val in builder_kw.items():
        b = getattr(b, name)(*val) if isinstance(val, tuple) \
            else getattr(b, name)(val)
    b.build().fit(ListDataSetIterator(batches or _batches()), epochs=epochs)
    return net


def _assert_params(a, b, bitexact=True, atol=5e-7):
    """Param comparison helper: ``bitexact=True`` pins byte equality;
    otherwise float32-resolution closeness (the TP rules genuinely
    reassociate one contraction's partial sums — see the composed test)."""
    for k in a.params:
        for p in a.params[k]:
            x = np.asarray(a.params[k][p])
            y = np.asarray(b.params[k][p])
            if bitexact:
                np.testing.assert_array_equal(
                    x, y, err_msg=f"param {k}/{p} not bit-identical")
            else:
                np.testing.assert_allclose(x, y, rtol=1e-6, atol=atol,
                                           err_msg=f"param {k}/{p}")


# ----------------------------------------------------------- MeshSpec
def test_auto_factor_balances_extents_deterministically():
    assert auto_factor(8, 1) == (8,)
    assert auto_factor(8, 2) == (4, 2)
    assert auto_factor(8, 3) == (2, 2, 2)
    assert auto_factor(12, 2) == (4, 3)
    assert auto_factor(1, 2) == (1, 1)


def test_meshspec_auto_factorizes_and_respects_fixed_extents():
    # the old degenerate default piled all 8 devices on the first axis
    spec = MeshSpec(axes=(DATA_AXIS, MODEL_AXIS))
    assert spec.resolve_shape(8) == (4, 2)
    m = spec.build()
    assert dict(m.shape) == {"data": 4, "model": 2}
    # a fixed model extent leaves the data extent to auto-factorize
    spec = MeshSpec(axes=(DATA_AXIS, MODEL_AXIS), shape=(None, 2))
    assert spec.resolve_shape(8) == (4, 2)
    # -1 is the same auto spelling
    spec = MeshSpec(axes=(DATA_AXIS, MODEL_AXIS), shape=(-1, 4))
    assert spec.resolve_shape(8) == (2, 4)


def test_meshspec_validation_is_loud_and_actionable():
    with pytest.raises(ValueError, match="duplicate"):
        MeshSpec(axes=(DATA_AXIS, DATA_AXIS))
    with pytest.raises(ValueError, match="at least one axis"):
        MeshSpec(axes=())
    with pytest.raises(ValueError, match="non-positive"):
        MeshSpec(axes=(DATA_AXIS,), shape=(0,))
    with pytest.raises(ValueError, match="2 extents for 1 axes"):
        MeshSpec(axes=(DATA_AXIS,), shape=(4, 2))
    # fixed extents that don't divide the device count name the numbers
    with pytest.raises(ValueError, match="multiple of 3.*8"):
        MeshSpec(axes=(DATA_AXIS, MODEL_AXIS), shape=(None, 3)).build()
    # fully-fixed shapes that under-cover tell the operator what to do
    with pytest.raises(ValueError, match="covers 4.*8 are available"):
        MeshSpec(axes=(DATA_AXIS, MODEL_AXIS), shape=(2, 2)).build()


def test_make_mesh_routes_through_meshspec():
    # multi-axis default auto-factorizes instead of the degenerate [n, 1]
    m = make_mesh(axes=(DATA_AXIS, MODEL_AXIS))
    assert dict(m.shape) == {"data": 4, "model": 2}
    # explicit shapes are preserved; single-axis default takes everything
    m = make_mesh(axes=(DATA_AXIS, MODEL_AXIS), shape=(2, 4))
    assert dict(m.shape) == {"data": 2, "model": 4}
    assert dict(make_mesh().shape) == {"data": 8}
    with pytest.raises(ValueError):
        make_mesh(axes=(DATA_AXIS,), shape=(3,))


def test_require_axes_names_the_missing_axis_and_the_fix():
    m = make_mesh(axes=(DATA_AXIS,))
    with pytest.raises(ValueError, match="model.*MeshSpec"):
        require_axes(m, (MODEL_AXIS,), style="composed step")
    assert require_axes(m, (DATA_AXIS, None)) is m   # None entries skipped


def test_zero_update_specs_compose_with_base_tp_specs():
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(axes=(DATA_AXIS, MODEL_AXIS), shape=(4, 2))
    tree = {"w1": np.zeros((16, 16)), "w0": np.zeros((6, 16)),
            "b": np.zeros((3,))}
    base = {"w1": NamedSharding(mesh, P(None, MODEL_AXIS)),
            "w0": NamedSharding(mesh, P(None, MODEL_AXIS)),
            "b": NamedSharding(mesh, P())}
    specs = zero_update_specs(tree, mesh, DATA_AXIS, base=base)
    # the data axis takes the largest dim TP left free
    assert specs["w1"].spec == P(DATA_AXIS, MODEL_AXIS)
    # 6 is not divisible by data=4: the base TP sharding is kept as-is
    assert specs["w0"].spec == P(None, MODEL_AXIS)
    # no divisible free dim at all: replicated base stays replicated
    assert specs["b"].spec == P()
    # without a base, behavior matches the classic 1-D rule (later-dim tie)
    solo = zero_update_specs({"w1": np.zeros((16, 16))}, mesh, DATA_AXIS)
    assert solo["w1"].spec == P(None, DATA_AXIS)
    # a base rule that already claims the ZeRO axis keeps its spec as-is
    # instead of building an invalid duplicate-axis PartitionSpec
    # (review finding)
    dup = zero_update_specs(
        {"w": np.zeros((16, 16))}, mesh, DATA_AXIS,
        base={"w": NamedSharding(mesh, P(None, DATA_AXIS))})
    assert dup["w"].spec == P(None, DATA_AXIS)


# ------------------------------------------- composed 2-D fits (tentpole)
def test_2d_mesh_pure_dp_and_zero_bit_identical_to_1d_twin():
    """THE substrate acceptance: moving a DP fit onto a 2-D data × model
    mesh changes NOTHING — bit-identical params to the 1-D twin with the
    same data extent — and ZeRO (ws/fsdp) riding the data axis of that
    2-D mesh stays bit-identical too (arXiv:2004.13336: reduce-scatter
    grads, update the local shard, all-gather weights ≡ replicated DP),
    while params/optimizer state genuinely live 1/N per device."""
    twin = _fit(_adam_net(), workers=4)

    mesh2 = make_mesh(axes=(DATA_AXIS, MODEL_AXIS), shape=(4, 2))
    pure = _fit(_adam_net(), mesh=mesh2)
    _assert_params(twin, pure, bitexact=True)

    ws = _fit(_adam_net(), mesh=mesh2, weight_update_sharding=True)
    _assert_params(twin, ws, bitexact=True)
    upd_specs = {str(l.sharding.spec)
                 for l in jax.tree_util.tree_leaves(ws.updater_state)
                 if hasattr(l, "sharding")}
    assert any(DATA_AXIS in s for s in upd_specs), upd_specs

    f = _fit(_adam_net(), mesh=mesh2, fsdp=True)
    _assert_params(twin, f, bitexact=True)
    w1 = f.params["1"]["W"]
    assert DATA_AXIS in str(w1.sharding.spec)
    # storage genuinely sharded: 1/4 of the bytes per device (data extent)
    assert w1.addressable_shards[0].data.nbytes == w1.nbytes // 4


def test_composed_2d_dp_tp_fit_matches_1d_twin():
    """DP × TP composed in ONE jitted step: the wrapper drives the data
    axis while megatron rules shard the model axis. The model split
    reassociates one contraction's partial sums (row-parallel psum), so
    the pin vs the 1-D DP twin is float32-resolution closeness (observed
    ~6e-8 = 1 ulp); the DP half of the composition is pinned bitwise by
    test_2d_mesh_pure_dp_and_zero_bit_identical_to_1d_twin. The model
    axis sharding must be REAL: half the param bytes per device."""
    twin = _fit(_adam_net(), workers=4)
    comp = _adam_net()
    pw = (ParallelWrapper.Builder(comp).workers(8).tensor_parallel(2)
          .build())
    assert dict(pw.mesh.shape) == {"data": 4, "model": 2}
    assert pw.workers_ == 4            # the wrapper drives the DATA axis
    pw.fit(ListDataSetIterator(_batches()), epochs=3)
    _assert_params(twin, comp, bitexact=False)
    w0 = comp.params["0"]["W"]
    assert MODEL_AXIS in str(w0.sharding.spec)
    assert w0.addressable_shards[0].data.nbytes == w0.nbytes // 2
    # the net still scores transparently after the composed fit
    assert np.isfinite(comp.score(_batches()[0]))


def test_composed_zero_rides_data_axis_of_composed_mesh():
    """ws/fsdp on the composed DP×TP mesh: ZeRO takes the dims TP left
    free, over the data axis — optimizer state leaves carry BOTH axes —
    and the trajectory matches the composed plain fit at float32
    resolution (the TP reassociation is shared; the ZeRO resharding adds
    none of its own — see the bitwise 2-D pin above)."""
    plain = _fit(_adam_net(), tensor_parallel=2, workers=8)
    ws = _fit(_adam_net(), tensor_parallel=2, workers=8,
              weight_update_sharding=True)
    _assert_params(plain, ws, bitexact=False)
    upd_specs = {str(l.sharding.spec)
                 for l in jax.tree_util.tree_leaves(ws.updater_state)
                 if hasattr(l, "sharding")}
    assert any(DATA_AXIS in s and MODEL_AXIS in s for s in upd_specs), \
        upd_specs

    f = _fit(_adam_net(), tensor_parallel=2, workers=8, fsdp=True)
    _assert_params(plain, f, bitexact=False)
    w1 = f.params["1"]["W"]
    # [16,16] W: model splits one dim, data the other → 1/8 per device
    assert {DATA_AXIS, MODEL_AXIS} <= set(
        s for s in w1.sharding.spec if s)
    assert w1.addressable_shards[0].data.nbytes == w1.nbytes // 8


def test_composed_step_keeps_a_closed_jit_set():
    """Size churn on the composed 2-D step: uniform iterator batches merge
    into ONE global-batch signature, so the step compiles exactly once
    across epochs and batch groups — zero retrace storms (the jitwatch
    proof that composition added no signature churn)."""
    from deeplearning4j_tpu.monitor.jitwatch import get_jit_registry
    reg = get_jit_registry()
    before = reg.table().get("sharding/dp_step", {})
    c0 = before.get("compiles", 0)
    s0 = before.get("storms", 0)
    net = _adam_net()
    pw = (ParallelWrapper.Builder(net).workers(8).tensor_parallel(2)
          .weight_update_sharding().build())
    pw.fit(ListDataSetIterator(_batches(8)), epochs=3)
    assert pw.iteration_count == 2 * 3       # 8 batches / 4 data slices
    after = reg.table()["sharding/dp_step"]
    assert after["compiles"] - c0 == 1, after
    assert after["storms"] - s0 == 0, after


def test_wrapper_tp_validation_is_loud():
    # composition is AVERAGING freq=1 only (like ws) — silent fallback
    # would fake the model split
    with pytest.raises(NotImplementedError, match="AVERAGING"):
        (ParallelWrapper.Builder(_adam_net()).workers(8)
         .tensor_parallel(2).averaging_frequency(2).build())
    with pytest.raises(NotImplementedError, match="AVERAGING"):
        (ParallelWrapper.Builder(_adam_net()).workers(8)
         .tensor_parallel(2)
         .training_mode(TrainingMode.SHARED_GRADIENTS).build())
    # an extent that cannot split anything is a config bug, not a no-op
    with pytest.raises(ValueError, match=">= 2"):
        ParallelWrapper(_adam_net(), tensor_parallel=1)
    # a wrapper mesh must carry the data axis it drives
    with pytest.raises(ValueError, match="data"):
        ParallelWrapper(_adam_net(),
                        mesh=make_mesh(jax.devices()[:2],
                                       axes=(MODEL_AXIS,)))
    # tp_rules with nowhere to shard them
    with pytest.raises(ValueError, match="model axis"):
        ParallelWrapper(_adam_net(), tp_rules={"^0/W$": None})
    # an explicit mesh whose model extent disagrees with the requested
    # one must not silently win (review finding)
    with pytest.raises(ValueError, match="model extent 2"):
        ParallelWrapper(_adam_net(), tensor_parallel=4,
                        mesh=make_mesh(axes=(DATA_AXIS, MODEL_AXIS),
                                       shape=(4, 2)))
    # rules naming an axis the mesh lacks fail loudly at the substrate,
    # not as a KeyError deep inside a tree_map (review finding)
    from jax.sharding import PartitionSpec as P
    from deeplearning4j_tpu.parallel import data_parallel_step
    with pytest.raises(ValueError, match="model.*MeshSpec"):
        data_parallel_step(_adam_net(), make_mesh(axes=(DATA_AXIS,)),
                           tp_rules={"^0/W$": P(None, MODEL_AXIS)})


def test_tensor_parallel_step_zero_flags():
    """ZeRO on tensor_parallel_step's own mesh: shard_update/shard_params
    layer the data axis over the TP rules (any-mesh ZeRO, not just the
    wrapper's), and a mesh without a data axis rejects loudly."""
    mesh = make_mesh(axes=(DATA_AXIS, MODEL_AXIS), shape=(4, 2))
    net = _adam_net()
    step, place = tensor_parallel_step(net, mesh, shard_update=True)
    place(net)
    upd_specs = {str(l.sharding.spec)
                 for l in jax.tree_util.tree_leaves(net.updater_state)
                 if hasattr(l, "sharding")}
    assert any(DATA_AXIS in s for s in upd_specs), upd_specs
    ds = _batches(1)[0]
    import jax.numpy as jnp
    itc = jnp.asarray(0, jnp.int32)
    key = net._next_rng()
    net.params, net.states, net.updater_state, loss = step(
        net.params, net.states, net.updater_state, itc, key,
        jnp.asarray(ds.features), jnp.asarray(ds.labels), None, None)
    assert np.isfinite(float(loss))
    with pytest.raises(ValueError, match="data"):
        tensor_parallel_step(_adam_net(),
                             make_mesh(jax.devices()[:2],
                                       axes=(MODEL_AXIS,)),
                             shard_update=True)


# ------------------------------------------------------- /profile block
def test_profile_mesh_block_reports_active_topology():
    from deeplearning4j_tpu.monitor.jitwatch import (profile_report,
                                                     render_profile_text)
    reset_mesh_registry()
    assert mesh_block() == {}
    net = _adam_net()
    pw = (ParallelWrapper.Builder(net).workers(8).tensor_parallel(2)
          .fsdp().build())
    pw.fit(ListDataSetIterator(_batches(4)), epochs=1)
    block = profile_report()["mesh"]
    row = block["sharding/dp_step"]
    assert row["axes"] == {"data": 4, "model": 2}
    assert row["devices"] == 8
    assert row["steps"] >= 1
    assert row["sharded_leaves"] > 0
    assert row["zero"] is True
    # sharded + replicated must cover the params+updater leaf census
    n_leaves = len(jax.tree_util.tree_leaves(net.params)) + \
        len(jax.tree_util.tree_leaves(net.updater_state))
    assert row["sharded_leaves"] + row["replicated_leaves"] == n_leaves
    txt = render_profile_text(profile_report())
    assert "# mesh (active parallel topologies)" in txt
    assert "sharding/dp_step" in txt
    assert "data=4" in txt and "model=2" in txt
