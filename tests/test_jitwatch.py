"""Compilation & memory observability (docs/OBSERVABILITY.md
"Compilation & memory"): monitored_jit accounting, the retrace-storm
detector (shape churn trips it, padded shapes don't), device-memory
gauges, the /profile step-anatomy report, and the ProfilerListener
close-on-error regression."""
import json
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                DataSet, Sgd)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.monitor import (TrainingHealthListener,
                                        TrainingHealthError, get_health,
                                        get_flight_recorder,
                                        get_jit_registry, get_registry,
                                        get_tracer, monitored_jit,
                                        profile_report, render_profile_text,
                                        sample_device_memory)


@pytest.fixture(autouse=True)
def _clean_monitor_state():
    """Storm/problem/flight state is process-global — isolate each test."""
    get_health().reset()
    get_flight_recorder().clear()
    get_jit_registry().drain_storms()
    yield
    get_health().reset()
    get_flight_recorder().clear()
    get_jit_registry().drain_storms()


def _net(seed=1):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(learning_rate=0.1)).activation("tanh").list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _ds(batch, rng):
    return DataSet(rng.normal(size=(batch, 4)).astype(np.float32),
                   np.eye(3, dtype=np.float32)[rng.integers(0, 3, batch)])


# ------------------------------------------------------------ monitored_jit
class TestMonitoredJit:
    def test_counts_compiles_vs_calls_and_registry_series(self):
        f = monitored_jit(lambda x: x * 3, name="test/triple")
        for _ in range(4):
            f(jnp.ones((4,)))
        assert f.calls == 4 and f.compiles == 1
        f(jnp.ones((6,)))             # new shape -> second variant
        assert f.compiles == 2 and f.calls == 5
        assert f.cache_miss_ratio == pytest.approx(0.4)
        reg = get_registry()
        assert reg.counter("jit_calls_total", fn="test/triple").value == 5
        assert reg.counter("jit_compiles_total", fn="test/triple").value == 2
        # histogram observed one sample per compile
        _, _, n = reg.histogram("jit_compile_seconds",
                                fn="test/triple").state()
        assert n == 2

    def test_compile_span_lands_on_trace_with_delta(self):
        f = monitored_jit(lambda x: x + 1, name="test/span_fn")
        f(jnp.ones((3,)))
        f(jnp.ones((5,)))
        evs = [e for e in get_tracer().events()
               if e["name"] == "compile/test/span_fn"]
        assert len(evs) >= 2
        assert evs[0]["args"]["signature_delta"] == "first compile"
        assert "float32[3]" in evs[1]["args"]["signature_delta"]
        assert "float32[5]" in evs[1]["args"]["signature_delta"]

    def test_cost_analysis_captured_per_variant(self):
        from deeplearning4j_tpu.monitor.jitwatch import wait_cost_captures
        f = monitored_jit(lambda a, b: a @ b, name="test/matmul")
        f(jnp.ones((8, 8)), jnp.ones((8, 8)))
        assert wait_cost_captures()    # capture is async by design
        row = get_jit_registry().table()["test/matmul"]
        assert row["flops"] > 0
        assert row["variants"] == 1

    def test_decorator_factory_form_and_wraps(self):
        @monitored_jit(name="test/deco", donate_argnums=(0,))
        def bump(x):
            """bump doc"""
            return x + 1
        out = bump(jnp.zeros((2,)))
        assert float(out.sum()) == 2.0
        assert bump.compiles == 1
        assert bump.__doc__ == "bump doc"

    def test_results_identical_to_plain_call(self):
        f = monitored_jit(lambda x: (x ** 2).sum(), name="test/sq")
        x = jnp.arange(5.0)
        assert float(f(x)) == float((x ** 2).sum())


# ------------------------------------------------------- retrace detection
class TestRetraceStorm:
    def test_shape_churn_fit_trips_storm_and_flight_event(self):
        net = _net()
        health = TrainingHealthListener(action="warn")
        net.set_listeners(health)
        rng = np.random.default_rng(0)
        for batch in (16, 17, 18, 19):   # ragged tails: 4 compiles
            net.fit(_ds(batch, rng))
        assert net._jit_step.compiles == 4
        problems = get_health().snapshot()["problems"]
        assert any("retrace" in p and "mln/step" in p for p in problems)
        storms = [e for e in get_flight_recorder().events()
                  if e["event"] == "retrace_storm" and e["fn"] == "mln/step"]
        assert storms, "no retrace_storm flight event"
        # the forensic payload: the delta names the argument whose shape
        # churned (the feature/label batch dimension)
        assert "->" in storms[0]["signature_delta"]
        assert "float32[1" in storms[0]["signature_delta"]
        # the listener drained the storm and applied its action
        assert any(kind == "retrace" for kind, _, _ in health.triggered)

    def test_padded_fit_records_exactly_one_compile_and_no_storm(self):
        net = _net(seed=2)
        net.set_listeners(TrainingHealthListener(action="warn"))
        rng = np.random.default_rng(1)
        for _ in range(4):               # fixed shape: bucketed/padded
            net.fit(_ds(16, rng))
        assert net._jit_step.compiles == 1
        assert net._jit_step.calls == 4
        problems = get_health().snapshot()["problems"]
        assert not any("mln/step" in p for p in problems)
        assert not [e for e in get_flight_recorder().events()
                    if e["event"] == "retrace_storm"
                    and e["fn"] == "mln/step"]

    def test_raise_action_applies_to_drained_storm(self):
        lst = TrainingHealthListener(action="raise")   # armed first:
        # listeners only act on storms that fire while they watch
        f = monitored_jit(lambda x: x * 2, name="test/churn")
        for n in (3, 4, 5):              # 3 compiles within the window
            f(jnp.ones((n,)))
        with pytest.raises(TrainingHealthError) as ei:
            lst.iteration_done(object(), 0, 0.5)
        assert ei.value.kind == "retrace"

    def test_storm_from_another_fit_thread_is_requeued_not_fired(self):
        """A listener must not halt ITS model for a storm that fired on a
        different fit thread (= a different model's training); the storm is
        requeued so the owning thread's listener still sees it."""
        import threading
        bystander = TrainingHealthListener(action="raise")

        def churn():
            f = monitored_jit(lambda x: x * 2, name="test/other_thread")
            for n in (3, 4, 5):
                f(jnp.ones((n,)))

        t = threading.Thread(target=churn)
        t.start()
        t.join(30)
        # the storm fired on the worker thread; the main-thread listener
        # must neither raise nor destructively consume it
        bystander.iteration_done(object(), 0, 0.5)
        assert not bystander.triggered
        pending = get_jit_registry().drain_storms()
        assert [s["fn"] for s in pending] == ["test/other_thread"]

    def test_watch_retrace_false_ignores_storms(self):
        lst = TrainingHealthListener(action="raise", watch_retrace=False)
        f = monitored_jit(lambda x: x * 2, name="test/churn2")
        for n in (3, 4, 5):
            f(jnp.ones((n,)))
        lst.iteration_done(object(), 0, 0.5)   # no raise
        assert not lst.triggered
        get_jit_registry().drain_storms()      # leave no storm behind


# --------------------------------------------------------- memory + profile
class TestMemoryAndProfile:
    def test_sample_device_memory_graceful_and_counts_buffers(self):
        keep = jnp.ones((16,))
        out = sample_device_memory()       # CPU: no allocator stats
        assert out["live_buffers"] is not None and out["live_buffers"] >= 1
        assert get_registry().gauge("device_live_buffers").value >= 1
        del keep

    def test_profile_endpoint_shows_three_named_fns(self):
        from deeplearning4j_tpu.ui import UIServer, InMemoryStatsStorage
        net = _net(seed=3)
        rng = np.random.default_rng(2)
        ds = _ds(16, rng)
        net.fit(ds)                                   # mln/step
        net.output(ds.features)                       # mln/output
        net.score(ds)                                 # mln/score
        from deeplearning4j_tpu.monitor.jitwatch import wait_cost_captures
        assert wait_cost_captures()    # flops land asynchronously
        ui = UIServer(port=0)
        ui.attach(InMemoryStatsStorage())
        port = ui.start()
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/profile", timeout=10) as r:
                rep = json.loads(r.read())
            named = {n: row for n, row in rep["jit"].items()
                     if n in ("mln/step", "mln/output", "mln/score")}
            assert len(named) == 3
            for row in named.values():
                assert row["compiles"] >= 1
                assert row["compile_seconds"] > 0
                assert row["flops"] > 0
            assert rep["steps"]["iterations"] >= 1
            assert rep["memory"]["live_buffers"] is not None
            # text rendering serves too
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/profile?format=text",
                    timeout=10) as r:
                text = r.read().decode()
            assert "mln/step" in text and "# device memory" in text
            # /metrics scrape carries the jit + memory series
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
                metrics = r.read().decode()
            assert 'jit_compiles_total{fn="mln/step"}' in metrics
            assert "device_live_buffers" in metrics
        finally:
            ui.stop()

    def test_profile_report_and_text_render_locally(self):
        f = monitored_jit(lambda x: x - 1, name="test/report")
        f(jnp.ones((2,)))
        rep = profile_report()
        assert "test/report" in rep["jit"]
        text = render_profile_text(rep)
        assert "test/report" in text


# --------------------------------------------- ProfilerListener error seam
class _Exploder:
    """Raises out of the fit loop mid-window (listener-bus member)."""
    def __init__(self, at_iteration):
        self.at = at_iteration

    def iteration_done(self, model, iteration, score):
        if iteration >= self.at:
            raise RuntimeError("boom")

    def on_epoch_start(self, model, epoch):
        pass

    def on_epoch_end(self, model, epoch):
        pass


def test_profiler_listener_closes_when_fit_raises(tmp_path):
    """Regression (PR 5 satellite): a fit that raises mid-trace-window must
    close the process-global jax.profiler trace — leaking it breaks the
    NEXT start_trace."""
    from deeplearning4j_tpu.utils.profiling import ProfilerListener
    net = _net(seed=4)
    prof = ProfilerListener(str(tmp_path / "t1"), start_iteration=1,
                            num_iterations=100)   # window never fills
    net.set_listeners(prof, _Exploder(at_iteration=2))
    rng = np.random.default_rng(3)
    ds = _ds(16, rng)
    with pytest.raises(RuntimeError, match="boom"):
        for _ in range(6):
            net.fit(ds)
    assert not prof._active, "jax.profiler trace leaked past the raise"
    # the proof the leak is fixed: a fresh trace window starts cleanly
    import jax
    jax.profiler.start_trace(str(tmp_path / "t2"))
    jax.profiler.stop_trace()
