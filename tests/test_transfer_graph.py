"""ComputationGraph transfer learning (VERDICT item 8; reference
``TransferLearning.GraphBuilder`` + ``TransferLearningHelper.java``):
freeze subgraph, replace outputs, featurize — done-criterion test fine-tunes
zoo ResNet50's head."""
import numpy as np
import pytest
import jax

from deeplearning4j_tpu import (NeuralNetConfiguration, InputType, DataSet,
                                ListDataSetIterator, Sgd, Adam)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer, FrozenLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.transferlearning import (TransferLearning,
                                                    TransferLearningHelper,
                                                    GraphTransferLearningHelper,
                                                    FineTuneConfiguration)
from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.models.zoo import ResNet50


def _small_cg(seed=3):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(learning_rate=1e-2)).activation("tanh")
            .graph_builder()
            .add_inputs("in")
            .add_layer("d0", DenseLayer(n_in=6, n_out=8), "in")
            .add_layer("d1", DenseLayer(n_in=8, n_out=8), "d0")
            .add_layer("out", OutputLayer(n_in=8, n_out=3, activation="softmax",
                                          loss="mcxent"), "d1")
            .set_outputs("out")
            .set_input_types(InputType.feed_forward(6))
            .build())
    return ComputationGraph(conf).init()


def _ds(n=16, n_in=6, n_out=3, seed=0):
    rng = np.random.default_rng(seed)
    return DataSet(rng.normal(size=(n, n_in)).astype(np.float32),
                   np.eye(n_out, dtype=np.float32)[rng.integers(0, n_out, n)])


def test_graph_builder_freeze_and_replace():
    net = _small_cg()
    orig_d0 = np.asarray(net.params["d0"]["W"]).copy()
    orig_d1 = np.asarray(net.params["d1"]["W"]).copy()

    new = (TransferLearning.GraphBuilder(net)
           .fine_tune_configuration(
               FineTuneConfiguration.builder().updater(Sgd(learning_rate=5e-2))
               .build())
           .set_feature_extractor("d0")
           .n_out_replace("out", 4)
           .build())
    assert isinstance(new.conf.vertices["d0"], FrozenLayer)
    assert not isinstance(new.conf.vertices["d1"], FrozenLayer)
    # d0/d1 params carried over; out re-initialized at new width
    np.testing.assert_array_equal(np.asarray(new.params["d0"]["W"]), orig_d0)
    np.testing.assert_array_equal(np.asarray(new.params["d1"]["W"]), orig_d1)
    assert new.params["out"]["W"].shape == (8, 4)

    ds = _ds(n_out=4)
    new.fit(ds)
    # frozen layer unchanged by training; downstream layers moved
    np.testing.assert_array_equal(np.asarray(new.params["d0"]["W"]), orig_d0)
    assert np.abs(np.asarray(new.params["d1"]["W"]) - orig_d1).max() > 0


def test_graph_builder_remove_and_add_vertex():
    net = _small_cg()
    new = (TransferLearning.GraphBuilder(net)
           .remove_vertex_and_connections("out")
           .add_layer("head", DenseLayer(n_in=8, n_out=5,
                                         activation="relu"), "d1")
           .add_layer("out2", OutputLayer(n_in=5, n_out=2,
                                          activation="softmax",
                                          loss="mcxent"), "head")
           .set_outputs("out2")
           .build())
    assert "out" not in new.conf.vertices
    ds = _ds(n_out=2)
    s0 = new.score(ds)
    new.fit(ListDataSetIterator([ds]), epochs=10)
    assert new.score(ds) < s0


def test_graph_nout_replace_cascades_nin():
    net = _small_cg()
    new = (TransferLearning.GraphBuilder(net)
           .n_out_replace("d0", 12)
           .build())
    assert new.params["d0"]["W"].shape == (6, 12)
    assert new.params["d1"]["W"].shape == (12, 8)  # nIn re-derived
    new.fit(_ds())  # trains fine at the new widths


def test_graph_transfer_helper_featurize():
    net = _small_cg()
    helper = TransferLearningHelper(net, "d0")
    assert isinstance(helper, GraphTransferLearningHelper)
    ds = _ds(8)
    mds = helper.featurize(ds)
    assert isinstance(mds, MultiDataSet)
    assert mds.features[0].shape == (8, 8)  # d0 activations
    # featurized output == full-graph output for the unfrozen tail
    full = np.asarray(net.output(ds.features))
    tail = np.asarray(helper.output_from_featurized(mds.features[0]))
    np.testing.assert_allclose(tail, full, rtol=1e-5, atol=1e-6)
    helper.fit_featurized(mds)  # trains without touching the frozen block


@pytest.mark.slow
def test_finetune_zoo_resnet50_head():
    """VERDICT done-criterion: fine-tune zoo ResNet50's head (new class
    count), body frozen, params carried over."""
    net = ResNet50(num_classes=4, input_shape=(3, 32, 32)).init()
    stem_w = np.asarray(net.params["stem-conv"]["W"]).copy()

    new = (TransferLearning.GraphBuilder(net)
           .fine_tune_configuration(
               FineTuneConfiguration.builder().updater(Adam(learning_rate=1e-3))
               .build())
           .set_feature_extractor("gap")
           .n_out_replace("output", 10)
           .build())
    assert new.params["output"]["W"].shape[-1] == 10
    assert isinstance(new.conf.vertices["stem-conv"], FrozenLayer)
    np.testing.assert_array_equal(np.asarray(new.params["stem-conv"]["W"]),
                                  stem_w)

    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(4, 3, 32, 32)).astype(np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, 4)])
    head_before = np.asarray(new.params["output"]["W"]).copy()
    new.fit(ds)
    assert np.isfinite(float(new.score_))
    # body frozen, head moved
    np.testing.assert_array_equal(np.asarray(new.params["stem-conv"]["W"]),
                                  stem_w)
    assert np.abs(np.asarray(new.params["output"]["W"]) - head_before).max() > 0
