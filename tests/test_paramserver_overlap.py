"""Latency-hiding training hot loop (paramserver/overlap.py + the
``overlap=True`` mode of paramserver/training.py).

The acceptance scenarios from the overlapped-comms pass:

- with an injected ≥5 ms per-push transport delay, overlap mode's
  steps/sec beats sync mode, and the phase accounting proves WHY (wall
  step time < Σ phases: the comms genuinely ran under the compute);
- sync mode (the default) stays bit-identical to the pre-overlap loop —
  pinned against a hand-rolled twin of the old blocking code path;
- the lossless threshold-0 fast path (exact f32 wire frames, apply the
  device-resident update) is bit-identical to the encode→decode→h2d
  bounce it replaces;
- a shard server dying MID-OVERLAP hands its decoded mass back through
  the comms worker into the accumulator residual (never lost);
- epoch end / close() drain the in-flight round — no silently dropped
  pushes, and the master stays reusable.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                DataSet, ListDataSetIterator, Sgd)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.monitor import get_flight_recorder, get_registry
from deeplearning4j_tpu.parallel.accumulation import (
    EncodedGradientsAccumulator, deserialize_encoded, serialize_encoded,
    threshold_decode)
from deeplearning4j_tpu.paramserver import (
    CommsPipeline, ParameterServer, ParameterServerClient,
    ParameterServerTrainingMaster, ShardedParameterServerGroup,
    async_device_get, flatten_params, set_params_from_flat)
from deeplearning4j_tpu.paramserver.overlap import start_device_get


def _net(n_in=6, hidden=16, classes=4, seed=11):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(learning_rate=5e-2)).activation("tanh").list()
            .layer(DenseLayer(n_in=n_in, n_out=hidden))
            .layer(OutputLayer(n_in=hidden, n_out=classes,
                               activation="softmax", loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _batches(n=8, rows=16, n_in=6, classes=4, seed=3):
    rng = np.random.default_rng(seed)
    return [DataSet(rng.normal(size=(rows, n_in)).astype(np.float32),
                    np.eye(classes, dtype=np.float32)[
                        rng.integers(0, classes, rows)])
            for _ in range(n)]


# -------------------------------------------------------- pipeline units
def test_comms_pipeline_depth_one_error_and_close():
    with CommsPipeline() as p:
        assert not p.inflight()
        p.submit(lambda: 41 + 1, label="ok")
        assert p.inflight()
        # bounded in-flight depth 1: a second submit before drain is a
        # PROGRAMMING error, not a queue
        with pytest.raises(RuntimeError):
            p.submit(lambda: None, label="second")
        assert p.drain() == 42
        assert not p.inflight()
        # a job's exception surfaces at drain, on the caller's thread...
        p.submit(lambda: 1 // 0, label="boom")
        with pytest.raises(ZeroDivisionError):
            p.drain()
        # ...and leaves the pipeline usable
        p.submit(lambda: "ok", label="after")
        assert p.drain() == "ok"
    with pytest.raises(RuntimeError):
        p.submit(lambda: None, label="closed")


def test_async_device_get_matches_blocking_fetch():
    tree = {"a": jnp.arange(6, dtype=jnp.float32).reshape(2, 3),
            "b": [jnp.ones(4, jnp.float32), jnp.asarray(3.5, jnp.float32)]}
    start_device_get(tree)          # starting early twice is harmless
    got = async_device_get(tree)
    want = jax.tree_util.tree_map(np.asarray, tree)
    got_l, got_def = jax.tree_util.tree_flatten(got)
    want_l, want_def = jax.tree_util.tree_flatten(want)
    assert got_def == want_def
    for g, w in zip(got_l, want_l):
        assert isinstance(g, np.ndarray)
        np.testing.assert_array_equal(g, w)


# ------------------------------------------------- lossless wire (thr=0)
def test_exact_wire_frame_roundtrip_and_decode():
    idx = np.array([0, 3, 7], np.int32)
    vals = np.array([0.125, -2.5, 1e-8], np.float32)
    blob = serialize_encoded((idx, vals, 0.0, 9))
    i2, s2, thr, n = deserialize_encoded(blob)
    assert s2.dtype == np.float32 and n == 9 and thr == 0.0
    np.testing.assert_array_equal(i2, idx)
    np.testing.assert_array_equal(s2, vals)   # bit-exact, incl. 1e-8
    dec = threshold_decode(i2, s2, thr, (9,))
    want = np.zeros(9, np.float32)
    want[idx] = vals
    np.testing.assert_array_equal(dec, want)
    # int8 quantized frames ride the original magic, byte-compatible
    q = serialize_encoded((idx, np.array([1, -1, 1], np.int8), 0.5, 9))
    i3, s3, thr3, _ = deserialize_encoded(q)
    assert s3.dtype == np.int8 and thr3 == 0.5
    np.testing.assert_array_equal(i3, idx)


def test_lossless_accumulator_is_exact_end_to_end():
    rng = np.random.default_rng(2)
    g = {"b": rng.normal(size=7).astype(np.float32),
         "w": (rng.normal(size=(5, 3)).astype(np.float32)
               * rng.integers(0, 2, (5, 3)))}   # real zeros stay off-wire
    acc = EncodedGradientsAccumulator(initial_threshold=0.0)
    assert acc.lossless
    dec = acc.store_update(g)
    for k in g:
        np.testing.assert_array_equal(dec[k], np.asarray(g[k], np.float32))
    assert not acc.has_residual                  # nothing withheld
    idx, vals, thr, n = acc.last_encoded
    assert vals.dtype == np.float32 and n == 22
    # the exact frame survives the real server arithmetic
    with ParameterServer(port=0) as srv:
        with ParameterServerClient(srv.address, max_retries=2,
                                   backoff=0.01) as c:
            vec = np.linspace(-1.0, 1.0, n).astype(np.float32)
            c.set_params(vec)
            c.push_update(serialize_encoded(acc.last_encoded))
            _, out = c.pull()
            dense = threshold_decode(idx, vals, thr, (n,))
            np.testing.assert_array_equal(out, vec - dense)


# ------------------------------------------- bit-equality vs the old loop
def _twin_fit_pre_overlap(master, net, batches):
    """Hand-rolled replica of the PRE-overlap sync loop: blocking
    per-leaf ``tree_map(np.asarray)`` fetch, encode, optimistic h2d apply
    of the decoded update (no lossless fast path), push, staleness pull —
    the exact op order the refactored sync mode must stay bit-identical
    to."""
    client = master._ensure_client()
    master._ensure_steps(net)
    acc = master.accumulator
    version, created = client.init_params(flatten_params(net.params))
    if not created:
        version, vec = client.pull()
        set_params_from_flat(net, vec)
    master.local_version = version
    for ds in batches:
        f = jnp.asarray(ds.features)
        l = jnp.asarray(ds.labels)
        itc = jnp.asarray(net.iteration_count, jnp.int32)
        update, net.states, net.updater_state, loss = master._update_step(
            net.params, net.states, net.updater_state, itc,
            net._next_rng(), f, l, None, None)
        update_host = jax.tree_util.tree_map(np.asarray, update)
        decoded_own = acc.store_update(update_host)
        net.params = master._apply_step(
            net.params, jax.tree_util.tree_map(jnp.asarray, decoded_own))
        pushed_version, failed_mass = client.push_encoded(acc.last_encoded)
        if failed_mass is not None:
            acc.reinject(failed_mass)
        master._adopt_pushed_version(pushed_version)
        master._adopt_fresh(net, client,
                            client.pull_if_stale(master.local_version))
        net.iteration_count += 1
    return net


def _master(srv, threshold, **kw):
    return ParameterServerTrainingMaster(
        srv.address, staleness=0, threshold=threshold, backoff=0.01, **kw)


def test_sync_mode_bit_identical_to_pre_overlap_twin():
    batches = _batches(6)
    net_a, net_b = _net(seed=11), _net(seed=11)
    with ParameterServer(port=0) as sa, ParameterServer(port=0) as sb:
        ma, mb = _master(sa, 1e-3), _master(sb, 1e-3)
        ma.execute_training(net_a, ListDataSetIterator(batches))
        _twin_fit_pre_overlap(mb, net_b, batches)
        np.testing.assert_array_equal(flatten_params(net_a.params),
                                      flatten_params(net_b.params))
        np.testing.assert_array_equal(ma.accumulator._residual,
                                      mb.accumulator._residual)
        ma.close()
        mb.close()


def test_lossless_fast_path_bit_identical_to_bounce():
    """threshold=0 sync mode applies the device-resident update directly;
    the twin still does the encode→decode→h2d bounce. Same bits."""
    batches = _batches(6)
    net_a, net_b = _net(seed=5), _net(seed=5)
    with ParameterServer(port=0) as sa, ParameterServer(port=0) as sb:
        ma, mb = _master(sa, 0.0), _master(sb, 0.0)
        assert ma.accumulator.lossless
        ma.execute_training(net_a, ListDataSetIterator(batches))
        assert not ma.accumulator.has_residual   # lossless leaves nothing
        _twin_fit_pre_overlap(mb, net_b, batches)
        np.testing.assert_array_equal(flatten_params(net_a.params),
                                      flatten_params(net_b.params))
        ma.close()
        mb.close()


# --------------------------------------------------- the overlap win
def _phase_totals():
    """(ms-sum, n) per phase from the registry children — per-fit means
    come from deltas (the registry is process-global and cumulative)."""
    reg = get_registry()
    out = {}
    for p in ("compute", "d2h", "encode", "push"):
        _, total, n = reg.histogram(
            "train_step_phase_ms",
            "paramserver training hot-loop phase latency",
            phase=p).state()
        out[p] = (total, n)
    _, total, n = reg.histogram(
        "train_step_wall_ms",
        "paramserver training wall time per step").state()
    out["wall"] = (total, n)
    return out


def test_overlap_beats_sync_under_injected_push_latency():
    """THE acceptance: ≥5 ms injected per-push transport delay, same
    model, same data — overlap mode goes faster than sync mode, and the
    phase deltas prove the comms ran UNDER the compute (overlap wall
    total < Σ phase totals)."""
    delay_s, steps = 0.012, 8
    n_in, hidden, classes, rows = 128, 128, 10, 2048
    batches = _batches(steps, rows=rows, n_in=n_in, classes=classes)

    def run(overlap):
        net = _net(n_in=n_in, hidden=hidden, classes=classes, seed=7)
        with ParameterServer(port=0) as srv:
            client = ParameterServerClient(
                srv.address, staleness=0, max_retries=5, backoff=0.01,
                push_delay_s=delay_s)
            master = _master(srv, 1e-3, count_own_pushes=False,
                             client=client, overlap=overlap)
            master.execute_training(net,
                                    ListDataSetIterator(batches[:2]))
            p0 = _phase_totals()
            t0 = time.perf_counter()
            master.execute_training(net, ListDataSetIterator(batches))
            dt = time.perf_counter() - t0
            p1 = _phase_totals()
            master.close()
        delta = {k: (p1[k][0] - p0[k][0], p1[k][1] - p0[k][1]) for k in p1}
        return steps / dt, delta

    sps_sync, d_sync = run(overlap=False)
    sps_over, d_over = run(overlap=True)
    if not sps_over > sps_sync:
        # single-core boxes under full-suite load: scheduler noise can
        # eat the ~12 ms/step win in one trial — re-measure once before
        # failing (a genuinely broken overlap loses both trials)
        sps_sync, d_sync = run(overlap=False)
        sps_over, d_over = run(overlap=True)
    assert sps_over > sps_sync, (sps_over, sps_sync)
    # every phase was timed in both modes, once per step
    for mode in (d_sync, d_over):
        for p in ("compute", "d2h", "encode", "push", "wall"):
            assert mode[p][1] == steps, (p, mode[p])
    # overlap: wall < Σ phases (comms hid under compute); sync: phases
    # stack end to end, so wall covers at least their sum
    over_phase_sum = sum(d_over[p][0]
                        for p in ("compute", "d2h", "encode", "push"))
    assert d_over["wall"][0] < over_phase_sum, (d_over, over_phase_sum)
    sync_phase_sum = sum(d_sync[p][0]
                        for p in ("compute", "d2h", "encode", "push"))
    assert d_sync["wall"][0] >= sync_phase_sum * 0.99

    # the /profile training block renders the same story
    from deeplearning4j_tpu.monitor import (profile_report,
                                            render_profile_text)
    block = profile_report()["training"]
    assert set(block["phase_ms"]) >= {"compute", "d2h", "encode", "push"}
    assert block["overlap_active"] is True      # last fit ran overlapped
    assert "hidden_ms_total" in block and "wall_ms_total" in block
    text = render_profile_text(profile_report())
    assert "# training (paramserver hot-loop phases)" in text


# ------------------------------------------- fault + drain under overlap
def test_failed_mass_reinjected_mid_overlap():
    """A shard server killed mid-fit: the comms WORKER's push comes back
    with the dead shard's decoded mass, reinjects it into the residual,
    and training completes on the surviving shard — no exception, no
    lost mass."""
    batches = _batches(8)
    net = _net()
    rec = get_flight_recorder()
    n0 = len(rec.events())
    with ShardedParameterServerGroup(2) as group:
        master = ParameterServerTrainingMaster(
            group.address, staleness=0, threshold=1e-3, backoff=0.01,
            max_retries=1, overlap=True)
        reinjected = []
        orig = master.accumulator.reinject

        def spy(mass):
            reinjected.append(float(np.abs(mass).sum()))
            return orig(mass)

        master.accumulator.reinject = spy
        killed = []

        class Killer:
            def iteration_done(self, model, iteration, score):
                if iteration == 2 and not killed:
                    killed.append(group.kill(1))

        net.listeners = [Killer()]
        master.execute_training(net, ListDataSetIterator(batches))
        master.close()
    assert killed
    assert reinjected and max(reinjected) > 0.0
    events = [e["event"] for e in rec.events()[n0:]]
    assert "shard_server_down" in events
    assert master.accumulator.has_residual    # the mass is still pending


def test_overlap_drains_at_epoch_end_and_close_and_is_reusable():
    batches = _batches(6)
    net = _net()
    with ParameterServer(port=0) as srv:
        client = ParameterServerClient(srv.address, staleness=0,
                                       max_retries=2, backoff=0.01)
        master = _master(srv, 1e-3, client=client, overlap=True)
        master.execute_training(net, ListDataSetIterator(batches))
        # epoch end drained the last round: nothing in flight and every
        # step's push actually landed (none swallowed by the window)
        assert master._pipeline is not None
        assert not master._pipeline.inflight()
        assert client.metrics.snapshot()["counters"]["pushes"] == 6
        # the master (and its pipeline) are reusable across epochs
        master.execute_training(net, ListDataSetIterator(batches))
        assert client.metrics.snapshot()["counters"]["pushes"] == 12
        master.close()
        assert master._pipeline is None and master.client is None
        master.close()    # idempotent
