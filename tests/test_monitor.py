"""Unified monitor subsystem (deeplearning4j_tpu/monitor/ —
docs/OBSERVABILITY.md): registry semantics + concurrency, tracer export,
health watchdog, endpoint round-trips on a live UIServer, the
ParamServerMetrics facade regression, and the monitor CLI snapshot."""
import http.client
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                Sgd, DataSet)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.monitor import (MetricsRegistry, Tracer,
                                        TrainingHealthListener,
                                        TrainingHealthError, get_registry,
                                        get_tracer, get_health)
from deeplearning4j_tpu.ui import UIServer, InMemoryStatsStorage


def _net(seed=1, lr=0.1):
    conf = (NeuralNetConfiguration.builder().seed(seed)
            .updater(Sgd(learning_rate=lr)).activation("tanh")
            .list()
            .layer(DenseLayer(n_in=4, n_out=8))
            .layer(OutputLayer(n_in=8, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _ds(seed=0, n=16):
    rng = np.random.default_rng(seed)
    return DataSet(rng.normal(size=(n, 4)).astype(np.float32),
                   np.eye(3, dtype=np.float32)[rng.integers(0, 3, n)])


def _get(port, path):
    return urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                  timeout=10)


# ----------------------------------------------------------------- registry
class TestRegistry:
    def test_counter_gauge_histogram_and_render(self):
        reg = MetricsRegistry()
        reg.counter("reqs_total", "requests", route="/a").inc(3)
        reg.gauge("temp", "temperature").set(21.5)
        reg.histogram("lat_ms", "latency", op="push").observe(1.0)
        reg.histogram("lat_ms", op="push").observe(100.0)
        text = reg.render_prometheus()
        assert '# TYPE reqs_total counter' in text
        assert 'reqs_total{route="/a"} 3' in text
        assert "temp 21.5" in text
        assert '# TYPE lat_ms histogram' in text
        assert 'lat_ms_count{op="push"} 2' in text
        assert 'le="+Inf"' in text
        # cumulative buckets are monotone and end at n
        counts = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
                  if line.startswith("lat_ms_bucket")]
        assert counts == sorted(counts) and counts[-1] == 2

    def test_same_child_returned_and_type_conflict_raises(self):
        reg = MetricsRegistry()
        a = reg.counter("x_total", peer="0")
        b = reg.counter("x_total", peer="0")
        assert a is b
        assert reg.counter("x_total", peer="1") is not a
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("x_total")

    def test_threaded_increments_sum_exactly(self):
        reg = MetricsRegistry()
        c = reg.counter("hits_total")
        h = reg.histogram("h_ms")
        n_threads, per_thread = 8, 1000

        def work():
            for _ in range(per_thread):
                c.inc()
                h.observe(1.0)

        threads = [threading.Thread(target=work) for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * per_thread
        assert h.summary()["n"] == n_threads * per_thread

    def test_snapshot_shape(self):
        reg = MetricsRegistry()
        reg.counter("a_total", role="x").inc(2)
        reg.histogram("b_ms").observe(5.0)
        snap = reg.snapshot()
        assert snap["a_total"][0] == {"labels": {"role": "x"},
                                      "type": "counter", "value": 2.0}
        assert snap["b_ms"][0]["summary"]["n"] == 1.0

    def test_dump_json_roundtrip_rerenders_identically(self):
        """dump() is the OP_TELEMETRY wire form: sending it through JSON
        and re-rendering with render_prometheus_dump must reproduce the
        local exposition byte for byte; extra labels (the fleet's
        ``worker``) merge into every child."""
        from deeplearning4j_tpu.monitor import render_prometheus_dump
        reg = MetricsRegistry()
        reg.counter("reqs_total", "requests", route="/a").inc(3)
        reg.gauge("temp", "temperature").set(21.5)
        reg.histogram("lat_ms", "latency", op="push").observe(1.0)
        text = reg.render_prometheus()
        wire = json.loads(json.dumps(reg.dump()))
        assert render_prometheus_dump(wire) == text
        relabeled = render_prometheus_dump(wire, {"worker": "w9"})
        assert 'reqs_total{route="/a",worker="w9"} 3' in relabeled
        assert 'temp{worker="w9"} 21.5' in relabeled
        assert 'lat_ms_count{op="push",worker="w9"} 1' in relabeled


# ------------------------------------------------------------------- tracer
class TestTracer:
    def test_export_is_valid_chrome_trace_with_nesting(self):
        tr = Tracer()
        with tr.span("outer", cat="test", k=1):
            with tr.span("inner", cat="test"):
                time.sleep(0.002)
        # valid JSON round trip with the trace-event required fields
        doc = json.loads(json.dumps(tr.export()))
        evs = doc["traceEvents"]
        assert len(evs) == 2
        for e in evs:
            assert e["ph"] == "X"
            assert {"name", "cat", "ts", "dur", "pid", "tid"} <= set(e)
        inner = next(e for e in evs if e["name"] == "inner")
        outer = next(e for e in evs if e["name"] == "outer")
        assert outer["ts"] <= inner["ts"]
        assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1
        assert outer["args"]["k"] == 1
        # trace-context stamping: both spans share one trace, the inner
        # span parents to the outer one, the root has no parent
        assert inner["args"]["trace_id"] == outer["args"]["trace_id"]
        assert inner["args"]["parent_span_id"] == outer["args"]["span_id"]
        assert "parent_span_id" not in outer["args"]

    def test_ring_buffer_bounded(self):
        tr = Tracer(capacity=10)
        for i in range(25):
            with tr.span(f"s{i}"):
                pass
        evs = tr.export()["traceEvents"]
        assert len(evs) == 10
        assert evs[-1]["name"] == "s24"  # newest survive

    def test_ring_overflow_counts_drops(self):
        """Satellite: ring-buffer eviction is no longer silent — drops
        land on the instance AND in the registry's
        tracer_spans_dropped_total, which /metrics exposes."""
        counter = get_registry().counter(
            "tracer_spans_dropped_total",
            "spans evicted from the trace ring buffer")
        before = counter.value
        tr = Tracer(capacity=5)
        for i in range(12):
            with tr.span(f"s{i}"):
                pass
        assert tr.dropped == 7
        assert counter.value - before == 7
        assert "tracer_spans_dropped_total" in \
            get_registry().render_prometheus()

    def test_remote_parent_joins_trace(self):
        """span(parent=ctx) with a context that 'arrived over the wire'
        records a child of the REMOTE span — the server half of the
        propagation story, without a socket."""
        from deeplearning4j_tpu.monitor import SpanContext
        client_tr, server_tr = Tracer(), Tracer()
        with client_tr.span("rpc") as ctx:
            wire = SpanContext(ctx.trace_id, ctx.span_id)   # 16-byte header
            with server_tr.span("handle", parent=wire):
                pass
        handle = server_tr.events()[0]
        rpc = client_tr.events()[0]
        assert handle["args"]["trace_id"] == rpc["args"]["trace_id"]
        assert handle["args"]["parent_span_id"] == rpc["args"]["span_id"]

    def test_decorator(self):
        tr = Tracer()

        @tr.trace(cat="test")
        def add(a, b):
            return a + b

        assert add(1, 2) == 3
        assert tr.export()["traceEvents"][0]["name"].endswith("add")

    def test_fit_produces_nested_step_spans(self):
        tracer = get_tracer()
        tracer.clear()
        net = _net()
        ds = _ds()
        for _ in range(3):
            net.fit(ds)
        evs = tracer.export()["traceEvents"]
        steps = [e for e in evs if e["name"] == "step"]
        epochs = [e for e in evs if e["name"] == "epoch"]
        assert len(steps) >= 3 and epochs
        # every step nests inside some epoch span
        for st in steps:
            assert any(ep["ts"] <= st["ts"] and
                       st["ts"] + st["dur"] <= ep["ts"] + ep["dur"] + 1
                       for ep in epochs)


# ---------------------------------------------------------- flight recorder
class TestFlightRecorder:
    def test_bounded_ordered_and_dropped_counted(self):
        from deeplearning4j_tpu.monitor import FlightRecorder
        fr = FlightRecorder(capacity=4)
        for i in range(7):
            fr.record("e", i=i)
        evs = fr.events()
        assert len(evs) == 4 and fr.dropped == 3
        assert [e["i"] for e in evs] == [3, 4, 5, 6]         # newest win
        seqs = [e["seq"] for e in evs]
        assert seqs == sorted(seqs)                          # provable order

    def test_dump_jsonl_and_nonserializable_degrade(self, tmp_path):
        from deeplearning4j_tpu.monitor import FlightRecorder
        fr = FlightRecorder()
        fr.record("weird", obj=object())     # degrades to repr, not raise
        fr.record("plain", x=1)
        path = fr.dump(path=str(tmp_path / "fr.jsonl"))
        rows = [json.loads(line)
                for line in open(path).read().splitlines()]
        assert [r["event"] for r in rows] == ["weird", "plain"]
        assert "object" in rows[0]["obj"]
        assert fr.last_dump_path == path

    def test_halt_dumps_flight_recorder(self, tmp_path, monkeypatch):
        """The black-box contract: a TrainingHealthListener halt persists
        the event log to disk (DL4J_TPU_FLIGHT_DIR) without being asked."""
        from deeplearning4j_tpu.monitor import get_flight_recorder
        monkeypatch.setenv("DL4J_TPU_FLIGHT_DIR", str(tmp_path))
        rec = get_flight_recorder()
        rec.clear()
        rec.record("before_halt", marker=1)
        get_health().record_halt("test halt")
        try:
            dumps = list(tmp_path.glob("flightrec-*.jsonl"))
            assert dumps, "halt must leave a JSONL dump behind"
            rows = [json.loads(line) for line
                    in dumps[0].read_text().splitlines()]
            kinds = [r["event"] for r in rows]
            assert "before_halt" in kinds and kinds[-1] == "halt"
            assert rows[-1]["reason"] == "test halt"
        finally:
            get_health().reset()
            rec.clear()


# ------------------------------------------------------------------- health
class TestHealthListener:
    def test_nan_trigger_warn_records(self):
        lst = TrainingHealthListener(action="warn")
        net = _net()
        lst.iteration_done(net, 0, 0.5)
        lst.iteration_done(net, 1, float("nan"))
        assert [t[0] for t in lst.triggered] == ["nan"]

    def test_divergence_trigger_and_raise_action(self):
        lst = TrainingHealthListener(action="raise", divergence_window=3,
                                     divergence_factor=2.0)
        net = _net()
        for i, s in enumerate((1.0, 1.1, 1.05)):
            lst.iteration_done(net, i, s)
        with pytest.raises(TrainingHealthError, match="exceeds"):
            lst.iteration_done(net, 3, 5.0)

    def test_stall_trigger(self):
        lst = TrainingHealthListener(action="warn", stall_timeout=0.01)
        net = _net()
        lst.iteration_done(net, 0, 1.0)
        time.sleep(0.05)
        lst.iteration_done(net, 1, 1.0)
        assert [t[0] for t in lst.triggered] == ["stall"]

    def test_param_nan_scan(self):
        lst = TrainingHealthListener(action="warn", check_params_every=1)
        net = _net()
        net.params["0"]["W"] = np.asarray(net.params["0"]["W"]).copy()
        net.params["0"]["W"][0, 0] = np.inf
        lst.iteration_done(net, 0, 0.5)
        assert [t[0] for t in lst.triggered] == ["nan"]

    def test_halt_action_stops_fit(self):
        class HaltNow(TrainingHealthListener):
            def iteration_done(self, model, iteration, score):
                self._fire(model, "nan", iteration, "injected halt")

        net = _net()
        net.set_listeners(HaltNow(action="halt"))
        net.fit(_ds(), epochs=5)      # halts after the first minibatch
        assert net.iteration_count == 1
        assert get_health().snapshot()["halted"]
        # a fresh fit() supersedes the halt: without the listener the run
        # completes and /healthz goes healthy again
        net.set_listeners()
        net.fit(_ds(), epochs=2)
        assert net.iteration_count == 3
        assert not net.halt_requested
        assert get_health().snapshot()["halted"] is None
        get_health().reset()

    def test_bad_action_rejected(self):
        with pytest.raises(ValueError, match="action"):
            TrainingHealthListener(action="explode")


# ---------------------------------------------------------------- endpoints
class TestEndpoints:
    def test_metrics_healthz_trace_roundtrip(self):
        get_health().reset()
        net = _net()
        ds = _ds()
        for _ in range(3):
            net.fit(ds)

        # paramserver traffic so /metrics carries push/pull histograms from
        # the same shared registry
        from deeplearning4j_tpu.paramserver import (ParameterServer,
                                                    ParameterServerClient)
        with ParameterServer(port=0) as srv:
            with ParameterServerClient(srv.address) as cli:
                cli.init_params(np.zeros(4, np.float32))
                cli.pull()

        srv_ui = UIServer(port=0)
        srv_ui.attach(InMemoryStatsStorage())
        port = srv_ui.start()
        try:
            with _get(port, "/metrics") as r:
                assert r.headers["Content-Type"].startswith("text/plain")
                text = r.read().decode()
            assert "training_iterations_total" in text
            assert "training_score" in text
            assert 'paramserver_pull_ms_count{role="client"}' in text
            assert 'paramserver_push_ms_count{role="server"}' in text
            assert 'paramserver_pull_ms_bucket{role="client",le=' in text
            assert "dataset_next_ms_count" in text

            with _get(port, "/healthz") as r:
                h = json.loads(r.read())
            assert h["status"] == "ok" and h["healthy"]
            assert h["last_iteration_age_s"] is not None

            with _get(port, "/trace") as r:
                doc = json.loads(r.read())
            names = {e["name"] for e in doc["traceEvents"]}
            assert "step" in names and "ps/pull" in names
        finally:
            srv_ui.stop()

    def test_healthz_flips_unhealthy_on_nan_score(self):
        get_health().reset()
        srv_ui = UIServer(port=0)
        srv_ui.attach(InMemoryStatsStorage())
        port = srv_ui.start()
        try:
            get_health().record_iteration(5, 0.4)
            with _get(port, "/healthz") as r:
                assert json.loads(r.read())["healthy"]
            # inject a NaN score the way the fit loop reports one
            get_health().record_iteration(6, float("nan"))
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(port, "/healthz")
            assert ei.value.code == 503
            body = json.loads(ei.value.read())
            assert body["nan"] and body["status"] == "unhealthy"
        finally:
            srv_ui.stop()
            get_health().reset()

    def test_post_content_length_cap_413(self):
        srv_ui = UIServer(port=0)
        srv_ui.attach(InMemoryStatsStorage())
        port = srv_ui.start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.putrequest("POST", "/remote")
            conn.putheader("Content-Length", str(64 << 20))  # 64 MB claim
            conn.putheader("Content-Type", "application/json")
            conn.endheaders()
            # server must answer 413 WITHOUT waiting for the body
            resp = conn.getresponse()
            assert resp.status == 413
            assert b"limit" in resp.read()
            conn.close()
            # negative Content-Length: reject, never read(-1) (which would
            # block until the client closes the socket)
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
            conn.putrequest("POST", "/remote")
            conn.putheader("Content-Length", "-1")
            conn.endheaders()
            assert conn.getresponse().status == 400
            conn.close()
        finally:
            srv_ui.stop()

    def test_host_parameter(self):
        srv_ui = UIServer(port=0, host="0.0.0.0")
        srv_ui.attach(InMemoryStatsStorage())
        port = srv_ui.start()
        try:
            with _get(port, "/healthz"):
                pass  # reachable via loopback while bound wide
        finally:
            srv_ui.stop()


# ------------------------------------------------------- facade regression
def test_paramserver_metrics_snapshot_shape_unchanged():
    """The registry migration must not change the snapshot() contract the
    listener bus and OP_STATS serve."""
    from deeplearning4j_tpu.paramserver import ParamServerMetrics
    from deeplearning4j_tpu.paramserver.metrics import COUNTERS
    m = ParamServerMetrics()
    m.record_push(3.0, 100)
    m.record_pull(1.0, 400)
    m.add("retries")
    snap = m.snapshot()
    assert set(snap) == {"counters", "push_latency", "pull_latency"}
    assert set(snap["counters"]) == set(COUNTERS)
    assert snap["counters"]["pushes"] == 1
    assert snap["counters"]["pull_bytes"] == 400
    assert snap["counters"]["retries"] == 1
    assert {"mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms",
            "n"} == set(snap["push_latency"])
    # per-instance isolation: a second facade starts from zero even though
    # both mirror into the same shared registry role
    m2 = ParamServerMetrics()
    assert m2.snapshot()["counters"]["pushes"] == 0


def test_transport_metrics_per_peer():
    """2-rank loopback mesh: gather/broadcast land per-peer byte counters
    and latency histograms in the shared registry."""
    from test_transport import _mesh
    chans = _mesh(2)
    try:
        a, b = chans
        t = threading.Thread(target=lambda: b.exchange(b"y" * 64),
                             daemon=True)
        t.start()
        got = a.exchange(b"x" * 64)
        t.join(10)
        assert got == [b"y" * 64]
        snap = get_registry().snapshot()
        rows = snap["transport_bytes_total"]
        dirs = {(r["labels"]["direction"], r["labels"]["peer"])
                for r in rows}
        assert ("out", "0") in dirs or ("out", "1") in dirs
        assert ("in", "0") in dirs or ("in", "1") in dirs
        assert any(r["summary"]["n"] >= 1
                   for r in snap["transport_recv_ms"])
    finally:
        for c in chans:
            c.close()


# ---------------------------------------------------------------------- CLI
def test_monitor_cli_local_snapshot(capsys):
    from deeplearning4j_tpu.main import main
    get_registry().counter("cli_probe_total").inc()
    assert main(["monitor"]) == 0
    out = capsys.readouterr().out
    assert "# TYPE cli_probe_total counter" in out
    assert '# health {"status"' in out


def test_monitor_cli_remote_and_json(tmp_path, capsys):
    from deeplearning4j_tpu.main import main
    get_health().reset()
    get_health().record_iteration(1, 0.9)
    srv_ui = UIServer(port=0)
    srv_ui.attach(InMemoryStatsStorage())
    port = srv_ui.start()
    try:
        trace_out = tmp_path / "trace.json"
        assert main(["monitor", "--url", f"127.0.0.1:{port}",
                     "--trace-out", str(trace_out)]) == 0
        out = capsys.readouterr().out
        assert "# TYPE" in out
        assert json.loads(trace_out.read_text())["traceEvents"] is not None

        assert main(["monitor", "--format", "json"]) == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["health"]["last_score"] == 0.9
        assert "metrics" in doc
    finally:
        srv_ui.stop()
