"""TPU pod provisioning tests (reference ``deeplearning4j-aws`` module:
Ec2BoxCreator / HostProvisioner / ClusterSetup / S3 staging — command
construction tested without cloud access, as the reference does)."""
from deeplearning4j_tpu.provision import (TpuPodConfig, TpuPodProvisioner,
                                          HostProvisioner, GcsStager,
                                          ClusterSetup)


def _cfg(**kw):
    return TpuPodConfig(name="bench-pod", zone="us-east5-b", **kw)


def test_create_delete_commands():
    p = TpuPodProvisioner(_cfg(project="proj-1", preemptible=True,
                               tags={"team": "ml"}))
    cmd = p.create_command()
    assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "bench-pod" in cmd and "--accelerator-type" in cmd
    assert cmd[cmd.index("--accelerator-type") + 1] == "v5litepod-16"
    assert "--preemptible" in cmd
    assert cmd[cmd.index("--labels") + 1] == "team=ml"
    d = p.delete_command()
    assert "delete" in d and "--quiet" in d


def test_host_provisioner_fans_out_to_all_workers():
    hosts = HostProvisioner(TpuPodProvisioner(_cfg()))
    cmd = hosts.run_command("pip install -e .")
    assert "--worker" in cmd and cmd[cmd.index("--worker") + 1] == "all"
    assert cmd[cmd.index("--command") + 1] == "pip install -e ."
    up = hosts.upload_command("train.py", "/tmp/train.py")
    assert "scp" in up and "bench-pod:/tmp/train.py" in up


def test_gcs_stager_commands():
    s = GcsStager("gs://my-bucket/data")
    up = s.upload_command("/local/imagenet", "imagenet")
    assert up[-1] == "gs://my-bucket/data/imagenet"
    down = s.download_command("imagenet", "/local/imagenet")
    assert down[-2] == "gs://my-bucket/data/imagenet"


def test_cluster_setup_plan_is_symmetric():
    """No parameter-server role: one identical launch command on all workers
    (multi-controller SPMD replaces the reference's ClusterSetup role split)."""
    plan = ClusterSetup(TpuPodProvisioner(_cfg()),
                        train_script="train.py",
                        env={"JAX_PLATFORMS": "tpu"}).plan()
    assert len(plan) == 3
    assert "create" in plan[0]
    assert any("train.py" in part for part in plan[1])
    launch = plan[2][plan[2].index("--command") + 1]
    assert launch == "JAX_PLATFORMS=tpu python3 train.py"


def test_runner_injection_executes_commands():
    calls = []
    p = TpuPodProvisioner(_cfg(), runner=lambda cmd: calls.append(cmd) or "ok")
    assert p.create(run=True) == "ok"
    assert p.delete(run=True) == "ok"
    assert calls[0][4] == "create" and calls[1][4] == "delete"
