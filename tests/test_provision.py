"""TPU pod provisioning tests (reference ``deeplearning4j-aws`` module:
Ec2BoxCreator / HostProvisioner / ClusterSetup / S3 staging — command
construction tested without cloud access, as the reference does)."""
from deeplearning4j_tpu.provision import (TpuPodConfig, TpuPodProvisioner,
                                          HostProvisioner, GcsStager,
                                          ClusterSetup)


def _cfg(**kw):
    return TpuPodConfig(name="bench-pod", zone="us-east5-b", **kw)


def test_create_delete_commands():
    p = TpuPodProvisioner(_cfg(project="proj-1", preemptible=True,
                               tags={"team": "ml"}))
    cmd = p.create_command()
    assert cmd[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "bench-pod" in cmd and "--accelerator-type" in cmd
    assert cmd[cmd.index("--accelerator-type") + 1] == "v5litepod-16"
    assert "--preemptible" in cmd
    assert cmd[cmd.index("--labels") + 1] == "team=ml"
    d = p.delete_command()
    assert "delete" in d and "--quiet" in d


def test_host_provisioner_fans_out_to_all_workers():
    hosts = HostProvisioner(TpuPodProvisioner(_cfg()))
    cmd = hosts.run_command("pip install -e .")
    assert "--worker" in cmd and cmd[cmd.index("--worker") + 1] == "all"
    assert cmd[cmd.index("--command") + 1] == "pip install -e ."
    up = hosts.upload_command("train.py", "/tmp/train.py")
    assert "scp" in up and "bench-pod:/tmp/train.py" in up


def test_gcs_stager_commands():
    s = GcsStager("gs://my-bucket/data")
    up = s.upload_command("/local/imagenet", "imagenet")
    assert up[-1] == "gs://my-bucket/data/imagenet"
    down = s.download_command("imagenet", "/local/imagenet")
    assert down[-2] == "gs://my-bucket/data/imagenet"


def test_cluster_setup_plan_is_symmetric():
    """No parameter-server role: one identical launch command on all workers
    (multi-controller SPMD replaces the reference's ClusterSetup role split)."""
    plan = ClusterSetup(TpuPodProvisioner(_cfg()),
                        train_script="train.py",
                        env={"JAX_PLATFORMS": "tpu"}).plan()
    assert len(plan) == 3
    assert "create" in plan[0]
    assert any("train.py" in part for part in plan[1])
    launch = plan[2][plan[2].index("--command") + 1]
    assert launch == "JAX_PLATFORMS=tpu python3 train.py"


def test_runner_injection_executes_commands():
    calls = []
    p = TpuPodProvisioner(_cfg(), runner=lambda cmd: calls.append(cmd) or "ok")
    assert p.create(run=True) == "ok"
    assert p.delete(run=True) == "ok"
    assert calls[0][4] == "create" and calls[1][4] == "delete"


# ----------------------------------------------------- lifecycle rehearsal
class _FakeCloud:
    """Scripted executor standing in for gcloud/gsutil: tracks pod
    existence, returns READY after a configurable number of describes, and
    can be told to fail specific commands — the rehearsal surface for the
    full ClusterSetup.java-style lifecycle."""

    def __init__(self, ready_after=2, fail_on=None):
        self.calls = []
        self.exists = False
        self.describes = 0
        self.ready_after = ready_after
        self.fail_on = fail_on or (lambda cmd: False)

    def __call__(self, cmd):
        import types
        self.calls.append(cmd)
        if self.fail_on(cmd):
            return types.SimpleNamespace(returncode=1, stdout="",
                                         stderr="injected failure")
        verb = cmd[4] if cmd[:4] == ["gcloud", "compute", "tpus",
                                     "tpu-vm"] else cmd[0]
        if verb == "create":
            self.exists = True
            return types.SimpleNamespace(returncode=0, stdout="", stderr="")
        if verb == "delete":
            self.exists = False
            return types.SimpleNamespace(returncode=0, stdout="", stderr="")
        if verb == "describe":
            if not self.exists:
                return types.SimpleNamespace(returncode=1, stdout="",
                                             stderr="NOT_FOUND")
            self.describes += 1
            state = ("state: READY" if self.describes >= self.ready_after
                     else "state: CREATING")
            return types.SimpleNamespace(returncode=0, stdout=state,
                                         stderr="")
        return types.SimpleNamespace(returncode=0, stdout="", stderr="")

    def verbs(self):
        return [c[4] if c[:4] == ["gcloud", "compute", "tpus", "tpu-vm"]
                else c[0] for c in self.calls]


def _lifecycle(tmp_path, cloud, **kw):
    from deeplearning4j_tpu.provision import PodLifecycle
    setup = ClusterSetup(TpuPodProvisioner(_cfg()), train_script="train.py",
                         env={"JAX_PLATFORMS": "tpu"})
    return PodLifecycle(
        setup, stager=GcsStager("gs://bkt/data"), datasets=["imagenet"],
        setup_commands=["pip install deeplearning4j_tpu"],
        journal_path=str(tmp_path / "journal.json"), executor=cloud,
        poll_interval_s=0.0, ready_timeout_s=30.0, **kw)


def test_lifecycle_full_bringup_ordering_and_teardown(tmp_path):
    """create → wait-ready (polls until READY) → provision all hosts →
    stage data → launch, strictly in order; teardown deletes and is
    idempotent on a gone pod."""
    cloud = _FakeCloud(ready_after=3)
    lc = _lifecycle(tmp_path, cloud)
    ran = lc.bringup()
    assert ran == ["create", "wait_ready", "provision", "stage_data",
                   "launch"]
    v = cloud.verbs()
    # describe (exists?) precedes create; polling describes follow; then
    # scp upload, ssh setup, ssh gsutil staging, ssh launch
    assert v[0] == "describe" and v[1] == "create"
    assert v.count("describe") >= 4            # exists-probe + 3 polls
    first_ssh = v.index("scp")
    assert all(x == "describe" for x in v[2:first_ssh])
    assert v[first_ssh:] == ["scp", "ssh", "ssh", "ssh"]
    # the staged dataset ends up in the fetchers' data dir on every host
    stage_cmd = cloud.calls[-2]
    assert "gsutil" in stage_cmd[stage_cmd.index("--command") + 1]
    launch = cloud.calls[-1]
    assert launch[launch.index("--command") + 1] == \
        "JAX_PLATFORMS=tpu python3 train.py"

    lc.teardown()
    assert cloud.verbs()[-1] == "delete" and not cloud.exists
    lc.teardown()                              # idempotent: no second delete
    assert cloud.verbs().count("delete") == 1


def test_lifecycle_reentry_skips_completed_steps(tmp_path):
    """Idempotent re-entry: a second bringup() with an intact journal runs
    NOTHING; after a mid-flight failure, re-entry resumes at the failed
    step without re-creating the pod."""
    cloud = _FakeCloud(ready_after=1)
    lc = _lifecycle(tmp_path, cloud)
    assert lc.bringup() == list(lc.STEPS)
    n_calls = len(cloud.calls)
    assert lc.bringup() == []                  # fully journaled: no-op
    # only the journal-trust existence probe hits the cloud, nothing else
    assert len(cloud.calls) == n_calls + 1
    assert cloud.verbs()[-1] == "describe"

    # fresh journal + failure during provision (scp): create/wait succeed,
    # bringup raises, journal holds the completed prefix
    cloud2 = _FakeCloud(ready_after=1,
                        fail_on=lambda cmd: "scp" in cmd)
    lc2 = _lifecycle(tmp_path / "b", cloud2)
    (tmp_path / "b").mkdir()
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="provision"):
        lc2.bringup()
    # heal the cloud; re-entry must NOT re-create (exists + journaled),
    # must resume at provision
    cloud2.fail_on = lambda cmd: False
    ran = lc2.bringup()
    assert ran == ["provision", "stage_data", "launch"]
    assert cloud2.verbs().count("create") == 1


def test_lifecycle_edited_step_reruns(tmp_path):
    """Changing a step's commands invalidates its journal hash: only that
    step (and nothing before it) re-runs."""
    cloud = _FakeCloud(ready_after=1)
    lc = _lifecycle(tmp_path, cloud)
    lc.bringup()
    lc.setup_commands.append("pip install extra-dep")   # edit provision
    ran = lc.bringup()
    assert ran == ["provision"]


def test_lifecycle_double_create_guard(tmp_path):
    """A pod that already exists (another operator / crashed run with a
    lost journal) is never double-created."""
    cloud = _FakeCloud(ready_after=1)
    cloud.exists = True                        # pre-existing pod
    lc = _lifecycle(tmp_path, cloud)
    ran = lc.bringup()
    assert ran == list(lc.STEPS)               # steps run (fresh journal)...
    assert "create" not in cloud.verbs()       # ...but no create command


def test_lifecycle_ready_timeout(tmp_path):
    """A pod that never reaches READY fails loudly within the budget."""
    cloud = _FakeCloud(ready_after=10**9)
    lc = _lifecycle(tmp_path, cloud)
    lc.ready_timeout_s = 0.2
    import pytest as _pytest
    with _pytest.raises(TimeoutError, match="READY"):
        lc.bringup()


def test_lifecycle_preempted_pod_invalidates_journal(tmp_path):
    """A completed journal is only trusted while the pod exists: after an
    external delete/preemption, bringup() starts over instead of reporting
    a dead pod as up."""
    cloud = _FakeCloud(ready_after=1)
    lc = _lifecycle(tmp_path, cloud)
    assert lc.bringup() == list(lc.STEPS)
    cloud.exists = False                       # preempted behind our back
    cloud.describes = 0
    ran = lc.bringup()
    assert ran == list(lc.STEPS)               # full re-bring-up
    assert cloud.verbs().count("create") == 2


def test_lifecycle_honors_provisioner_runner(tmp_path):
    """A runner injected on TpuPodProvisioner (the pre-existing seam) is
    used by PodLifecycle too — auth wrappers are not silently bypassed."""
    import types
    from deeplearning4j_tpu.provision import PodLifecycle
    calls = []

    def auth_runner(cmd):
        calls.append(cmd)
        if cmd[4] == "describe":
            return types.SimpleNamespace(returncode=0, stdout="state: READY",
                                         stderr="")
        return types.SimpleNamespace(returncode=0, stdout="", stderr="")

    prov = TpuPodProvisioner(_cfg(), runner=auth_runner)
    lc = PodLifecycle(ClusterSetup(prov, train_script="t.py"),
                      journal_path=str(tmp_path / "j.json"),
                      poll_interval_s=0.0)
    lc.bringup()
    assert calls, "provisioner runner must receive the lifecycle commands"


def test_lifecycle_stage_data_home_expansion(tmp_path):
    """The staged destination keeps $HOME expandable on the remote shell
    (a single-quoted literal '~' would stage into the wrong directory)."""
    cloud = _FakeCloud(ready_after=1)
    lc = _lifecycle(tmp_path, cloud)
    [cmd] = lc._step_commands("stage_data")
    remote = cmd[cmd.index("--command") + 1]
    assert '"$HOME"' in remote and "'~" not in remote
    assert remote.startswith("mkdir -p ")
    # retry-safe: a partial dst from a failed copy is removed before the
    # re-run, or `gsutil cp -r` would nest the dataset one level deeper
    assert "rm -rf" in remote and remote.index("rm -rf") < \
        remote.index("gsutil")
    assert "gs://bkt/data/imagenet" in remote
