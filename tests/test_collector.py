"""Scrape-plane fleet collector (docs/OBSERVABILITY.md "Scrape plane"):
the ``/telemetry`` one-round-trip bundle with seq-cursored flight
events, the pull-based :class:`TelemetryCollector` landing scrapes in
the fleet table, and THE fleet acceptance drill — two REAL replica
subprocesses (each owns its registry/tracer/flight recorder, exactly
the isolation the scrape plane exists for) scraped by a live
collector: fleet-scope SLO rules walk OK→PENDING→FIRING naming the
guilty replica with a trace id resolvable on THAT replica, a
mid-drill kill trips ``fleet_target_down``, recovery resolves
everything, and the whole incident reconstructs from ``/events``.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from deeplearning4j_tpu.monitor import (FleetState, ScrapeTarget,
                                        TelemetryCollector,
                                        default_fleet_scope_rules,
                                        get_fleet, telemetry_snapshot)
from deeplearning4j_tpu.monitor.flightrec import get_flight_recorder
from deeplearning4j_tpu.monitor.tracer import get_tracer
from deeplearning4j_tpu.ui import InMemoryStatsStorage, UIServer


def _get_json(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        body = json.loads(e.read().decode("utf-8"))
        e.close()
        return e.code, body


def _get_text(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.read().decode("utf-8")


def _post_predict(port, model="drill"):
    """One predict round trip; 500s (injected model faults) are DATA for
    the burn rule, so they come back as (code, body), never raise."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{model}/predict",
        data=json.dumps({"inputs": [[1.0, 2.0]]}).encode("utf-8"),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        body = json.loads(e.read().decode("utf-8"))
        e.close()
        return e.code, body


# ------------------------------------------------- /telemetry semantics
class TestTelemetrySnapshot:
    def test_prime_then_cursor_then_full_history(self):
        """No ``since_seq`` → the priming reply: ``last_seq`` only, NO
        events (a collector joining late must never replay history as
        fresh incidents). ``since_seq=<cursor>`` → only newer events.
        ``since_seq=-1`` is the explicit opt-in to full history."""
        rec = get_flight_recorder()
        rec.record("collector_unit_t1")
        prime = telemetry_snapshot()
        assert prime["flight_events"] == []
        assert prime["last_seq"] == rec.events()[-1]["seq"]
        for key in ("registry", "trace_events", "health", "exemplars"):
            assert key in prime

        rec.record("collector_unit_t2")
        fresh = telemetry_snapshot(since_seq=prime["last_seq"])
        assert [e["event"] for e in fresh["flight_events"]] \
            == ["collector_unit_t2"]
        assert fresh["last_seq"] > prime["last_seq"]

        full = telemetry_snapshot(since_seq=-1)
        names = [e["event"] for e in full["flight_events"]]
        assert "collector_unit_t1" in names and "collector_unit_t2" in names

    def test_endpoint_served_with_cursor_and_400(self):
        """Both server families route ``/telemetry`` through the shared
        ``_monitor_get`` — here the UI server: prime reply, cursored
        reply, and a non-int ``since_seq`` is a 400, not a 500."""
        ui = UIServer(port=0)
        ui.attach(InMemoryStatsStorage())
        port = ui.start()
        rec = get_flight_recorder()
        try:
            status, prime = _get_json(port, "/telemetry")
            assert status == 200
            assert prime["flight_events"] == []
            rec.record("collector_http_fresh")
            status, doc = _get_json(
                port, f"/telemetry?since_seq={prime['last_seq']}")
            assert status == 200
            assert "collector_http_fresh" in [
                e["event"] for e in doc["flight_events"]]
            status, err = _get_json(port, "/telemetry?since_seq=banana")
            assert status == 400 and "since_seq" in err["error"]
        finally:
            ui.stop()


# --------------------------------------------------- collector plumbing
class TestCollectorTick:
    def test_tick_lands_report_and_cursors_remote_events(self):
        """One tick against an in-process server: the reply lands as a
        fleet report (worker-labeled series on the merged dump), the
        cursor primes on the first scrape, and a flight event recorded
        between ticks is re-recorded locally WITH provenance."""
        ui = UIServer(port=0)
        ui.attach(InMemoryStatsStorage())
        port = ui.start()
        fleet = FleetState()
        c = TelemetryCollector(fleet=fleet, timeout_s=10.0)
        c.add_target("u0", f"127.0.0.1:{port}")
        rec = get_flight_recorder()
        try:
            res = c.tick()
            assert res["scraped"] == ["u0"] and not res["errors"]
            snap = c.snapshot()["targets"]["u0"]
            assert snap["up"] is True and isinstance(snap["cursor"], int)
            dump = c.fleet_dump()
            ups = {r["labels"]["target"]: r["value"]
                   for r in dump["fleet_target_up"]["children"]}
            assert ups == {"u0": 1.0}
            assert any(
                row.get("labels", {}).get("worker") == "u0"
                for fam in fleet.merged_dump().values()
                for row in fam.get("children", []))

            rec.record("collector_remote_boom", shard=3)
            c.tick()
            landed = [e for e in rec.events()
                      if e["event"] == "collector_remote_boom"
                      and e.get("target") == "u0"]
            assert landed, "cursor-fresh remote event must re-record " \
                           "locally with target provenance"
            assert landed[0].get("origin_seq") is not None
            assert landed[0].get("shard") == 3

            # one history sample + engine pass per tick (the upward loop)
            assert len(c.history.samples()) == 2
        finally:
            c.stop()
            ui.stop()

    def test_remove_target_drops_scrape_series_from_fleet_dump(self):
        """A retired target's stale ``fleet_target_up 0`` must not leak
        into the merged dump and trip gap rules forever."""
        c = TelemetryCollector(fleet=FleetState(), timeout_s=0.2)
        c.add_target("gone", "127.0.0.1:9")      # refused → up=0
        c.tick()
        assert [t.label for t in c.down_targets()] == ["gone"]
        assert "fleet_target_up" in c.fleet_dump()
        c.remove_target("gone")
        fam = c.fleet_dump().get("fleet_target_up")
        assert not fam or not [
            r for r in fam.get("children", [])
            if r.get("labels", {}).get("target") == "gone"]


# ------------------------------------------------ THE acceptance drill
# One replica subprocess: registers a flag-file-faultable model, starts
# an InferenceServer on an ephemeral port, prints the port, then blocks
# on stdin (kill/terminate is the drill's failure injection). It records
# a flight event BEFORE serving so the drill can prove cursor priming
# keeps pre-existing incident history from replaying in the collector.
_REPLICA_SRC = r"""
import os, sys, time
import numpy as np

flag = sys.argv[1]

class DrillModel:
    def __init__(self):
        self.n = 0
    def output(self, x, mask=None):
        x = np.asarray(x)
        if os.path.exists(flag):          # fault switch: slow + erroring
            time.sleep(0.08)
            self.n += 1
            if self.n % 2 == 0:
                raise RuntimeError("injected drill fault")
        return np.full((x.shape[0], 2), 1.0, np.float32)

from deeplearning4j_tpu.serving import InferenceServer
from deeplearning4j_tpu.monitor import get_flight_recorder

get_flight_recorder().record("preexisting_incident", origin="replica")
srv = InferenceServer()
srv.register("drill", DrillModel(), batch_buckets=(1,), linger_ms=0.0,
             max_queue_examples=64)
print(srv.start(port=0), flush=True)
sys.stdin.read()
"""


def _spawn_replica(flag_path, err_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"      # numpy model; never wait on a device
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    errf = open(err_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-c", _REPLICA_SRC, str(flag_path)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errf,
        text=True, env=env, cwd=root)
    box = {}

    def _read():
        box["line"] = proc.stdout.readline()

    t = threading.Thread(target=_read, daemon=True)
    t.start()
    t.join(120)
    line = (box.get("line") or "").strip()
    if not line:
        proc.kill()
        proc.wait(timeout=30)
        errf.close()
        with open(err_path) as f:
            raise RuntimeError(f"replica failed to start:\n{f.read()}")
    errf.close()
    return proc, int(line)


class TestFleetAcceptanceDrill:
    def test_two_replica_fleet_drill(self, tmp_path):
        """THE acceptance scenario, end to end: two real replica
        processes scraped by a live collector; a slow+erroring model on
        r1 walks ``fleet_p99_worst_replica`` and ``fleet_error_burn``
        through OK→PENDING→FIRING with the guilty replica named in the
        detail and an exemplar trace id resolvable on r1's own
        ``/trace``; killing r1 mid-drill trips ``fleet_target_down``;
        respawning resolves every rule with a ``fleet_target_recovered``
        edge; the whole incident reads back off ``/events``; and
        ``stop()`` leaves no collector thread behind."""
        fleet = get_fleet()
        fleet.clear()
        rec = get_flight_recorder()
        rec.clear()
        ui = UIServer(port=0)
        ui.attach(InMemoryStatsStorage())
        ui_port = ui.start()
        flag = tmp_path / "fault_r1"
        collector = TelemetryCollector(timeout_s=10.0)
        edges = []
        collector.engine.subscribe(
            lambda ev, payload: edges.append((ev, dict(payload))))
        collector.engine.add(*default_fleet_scope_rules(
            fleet=collector.fleet, windows=(1.5, 3.0),
            p99_target_ms=40.0, for_seconds=0.2))
        procs = []
        states = []
        step = [0]

        def beat(posts, per=2):
            """Drive ``per`` requests per listed replica, then one
            deterministic synthetic-time tick (0.5s per beat — 7 beats
            cover the 3s window with the quarter-window tolerance)."""
            for port in posts:
                for _ in range(per):
                    _post_predict(port)
            step[0] += 1
            res = collector.tick(now=t0 + 0.5 * step[0])
            states.append({r.name: r.state
                           for r in collector.engine.rules()})
            return res

        try:
            p0, port0 = _spawn_replica(tmp_path / "no_fault_r0",
                                       tmp_path / "r0.err")
            procs.append(p0)
            p1, port1 = _spawn_replica(flag, tmp_path / "r1.err")
            procs.append(p1)
            collector.add_target("r0", f"127.0.0.1:{port0}")
            collector.add_target("r1", f"127.0.0.1:{port1}")

            # live collector: start() scrapes immediately (interval far
            # beyond the drill so the deterministic beats own the clock)
            collector.start(interval_s=120.0)
            assert collector.running()
            assert "telemetry-collector" in [
                t.name for t in threading.enumerate()]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                targets = collector.snapshot()["targets"]
                if len(targets) == 2 and all(
                        v["up"] for v in targets.values()):
                    break
                time.sleep(0.05)
            else:
                pytest.fail(f"live scrape never landed: "
                            f"{collector.snapshot()}")
            time.sleep(0.25)          # let the first tick's sample+eval
            t0 = time.time()          # finish before synthetic beats

            # cursor priming: both replicas recorded incident history
            # BEFORE the first scrape; none of it replays locally
            assert not [e for e in rec.events()
                        if e["event"] == "preexisting_incident"]

            # ---- healthy baseline: windows covered, everything OK
            for _ in range(7):
                res = beat([port0, port1])
                assert not res["errors"], res
            assert states[-1] == {"fleet_error_burn": "OK",
                                  "fleet_p99_worst_replica": "OK",
                                  "fleet_target_down": "OK"}

            # merged surfaces while healthy: one GET /fleet serves both
            # replicas' series under stable worker labels; the merged
            # trace carries both replicas' spans exactly once
            text = _get_text(ui_port, "/fleet")
            assert 'worker="r0"' in text and 'worker="r1"' in text
            assert "fleet_worker_up" in text
            status, trace = _get_json(ui_port, "/fleet/trace")
            assert status == 200
            spans = [e for e in trace["traceEvents"]
                     if e.get("ph") == "X"
                     and (e.get("args") or {}).get("trace_id")]
            keys = [(e["args"]["trace_id"], e["args"].get("span_id"),
                     e["ts"]) for e in spans]
            assert spans and len(keys) == len(set(keys))
            assert len({e["pid"] for e in spans}) >= 2

            # ---- inject the fault on r1: slow forwards + one 500 per
            # two requests; both burn rules must walk the state machine
            flag.write_text("x")
            for _ in range(14):
                beat([port0, port1])
                if (states[-1]["fleet_p99_worst_replica"] == "FIRING"
                        and states[-1]["fleet_error_burn"] == "FIRING"):
                    break
            assert states[-1]["fleet_p99_worst_replica"] == "FIRING", \
                [(r.name, r.state, r.last_detail)
                 for r in collector.engine.rules()]
            assert states[-1]["fleet_error_burn"] == "FIRING"
            p99_walk = [s["fleet_p99_worst_replica"] for s in states]
            assert "PENDING" in p99_walk, p99_walk   # hold-down honored

            # the firing edge names the GUILTY replica and carries an
            # exemplar trace id resolvable against THAT replica's /trace
            fired = [p for ev, p in edges if ev == "alert_firing"
                     and p.get("rule") == "fleet_p99_worst_replica"]
            assert fired, edges
            assert "worker=r1" in (fired[-1].get("detail") or "")
            exemplar = fired[-1].get("exemplar_trace_id")
            assert exemplar
            status, rtrace = _get_json(port1, "/trace")
            assert exemplar in {
                (e.get("args") or {}).get("trace_id")
                for e in rtrace["traceEvents"]}

            # ---- kill r1 mid-drill: the scrape fails, liveness drops,
            # the gap rule fires, and the error counter shows the miss
            p1.kill()
            p1.wait(timeout=30)
            flag.unlink()                # respawn will come back healthy
            res = beat([port0])
            assert "r1" in res["errors"]
            assert [t.label for t in collector.down_targets()] == ["r1"]
            beat([port0])                # hold-down (0.2s < one beat)
            assert states[-1]["fleet_target_down"] == "FIRING"
            dump = collector.fleet_dump()
            ups = {r["labels"]["target"]: r["value"]
                   for r in dump["fleet_target_up"]["children"]}
            assert ups["r1"] == 0.0 and ups["r0"] == 1.0
            errs = {r["labels"]["target"]: r["value"]
                    for r in dump["fleet_scrape_errors_total"]["children"]}
            assert errs.get("r1", 0) >= 1
            assert any(e["event"] == "fleet_target_down"
                       and e.get("target") == "r1" for e in rec.events())

            # ---- recovery: respawn r1 (same label, new port), drive
            # healthy beats until the fault ages out of both windows
            p1b, port1b = _spawn_replica(flag, tmp_path / "r1b.err")
            procs.append(p1b)
            collector.add_target("r1", f"127.0.0.1:{port1b}")
            for _ in range(16):
                beat([port0, port1b])
                if states[-1] == {"fleet_error_burn": "OK",
                                  "fleet_p99_worst_replica": "OK",
                                  "fleet_target_down": "OK"}:
                    break
            assert states[-1] == {"fleet_error_burn": "OK",
                                  "fleet_p99_worst_replica": "OK",
                                  "fleet_target_down": "OK"}, \
                [(r.name, r.state, r.last_detail)
                 for r in collector.engine.rules()]
            assert any(e["event"] == "fleet_target_recovered"
                       and e.get("target") == "r1" for e in rec.events())
            # the respawned replica's pre-scrape history stays suppressed
            assert not [e for e in rec.events()
                        if e["event"] == "preexisting_incident"]
            assert {p.get("rule") for ev, p in edges
                    if ev == "alert_resolved"} >= {
                        "fleet_error_burn", "fleet_p99_worst_replica",
                        "fleet_target_down"}

            # ---- the incident reconstructs from GET /events alone
            status, evdoc = _get_json(ui_port, "/events")
            assert status == 200
            names = [e["event"] for e in evdoc["events"]]
            for needed in ("alert_firing", "fleet_target_down",
                           "fleet_target_recovered", "alert_resolved"):
                assert needed in names, names
            assert names.index("fleet_target_down") \
                < names.index("fleet_target_recovered")

            # ---- lifecycle: timed-join stop leaves no thread behind
            collector.stop()
            assert not collector.running()
            assert "telemetry-collector" not in [
                t.name for t in threading.enumerate()]
        finally:
            collector.stop()
            collector.engine.clear()
            fleet.clear()
            rec.clear()
            get_tracer().clear()
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
            ui.stop()
