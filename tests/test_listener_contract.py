"""Listener-bus interface-drift guard.

Every ``TrainingListener`` subclass in the package must only override hook
names/signatures defined on the base class: a listener defining
``on_epoch_finish`` (typo) or adding a positional arg to
``iteration_done`` would silently never fire / blow up at dispatch time as
the bus grows. This walks every package module, collects the full subclass
tree, and pins both rules."""
import importlib
import inspect
import pkgutil

import deeplearning4j_tpu
from deeplearning4j_tpu.optimize.listeners import TrainingListener


def _import_all_modules():
    """Import every package module so the subclass tree is complete.
    Modules with optional external deps are skipped, not failed."""
    skipped = []
    for info in pkgutil.walk_packages(deeplearning4j_tpu.__path__,
                                      deeplearning4j_tpu.__name__ + "."):
        if info.name.endswith("__main__"):
            continue  # importing it runs the CLI entry point
        try:
            importlib.import_module(info.name)
        except Exception as e:
            skipped.append((info.name, repr(e)))
    return skipped


def _all_subclasses(cls):
    out = set()
    for sub in cls.__subclasses__():
        out.add(sub)
        out |= _all_subclasses(sub)
    return out


def _hook_signatures():
    return {name: inspect.signature(fn)
            for name, fn in vars(TrainingListener).items()
            if not name.startswith("_") and callable(fn)}


def test_listener_subclasses_only_override_known_hooks():
    skipped = _import_all_modules()
    hooks = _hook_signatures()
    assert "iteration_done" in hooks  # the contract this test guards

    subclasses = _all_subclasses(TrainingListener)
    # the walk must actually have found the stock listeners — an empty or
    # tiny tree means the import sweep broke, not that the bus is clean
    names = {c.__name__ for c in subclasses}
    assert {"ScoreIterationListener", "PerformanceListener",
            "StatsListener", "ParamServerMetricsListener",
            "TrainingHealthListener"} <= names, (names, skipped)

    problems = []
    for cls in sorted(subclasses, key=lambda c: c.__qualname__):
        for name, member in vars(cls).items():
            if name.startswith("_") or not inspect.isfunction(member):
                continue
            if name in hooks:
                got = list(inspect.signature(member).parameters)
                want = list(hooks[name].parameters)
                if got != want:
                    problems.append(
                        f"{cls.__module__}.{cls.__qualname__}.{name} "
                        f"signature {got} != bus contract {want}")
            elif name.startswith("on_") or name in ("iterationDone",):
                # looks like a bus hook but the bus will never call it
                problems.append(
                    f"{cls.__module__}.{cls.__qualname__}.{name} looks "
                    f"like a listener hook but TrainingListener defines "
                    f"no such method (known hooks: {sorted(hooks)})")
    assert not problems, "\n".join(problems)
