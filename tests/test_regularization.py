"""Dropout family / weight noise / constraints tests (reference
``nn/conf/dropout``, ``weightnoise``, ``constraint`` families)."""
import numpy as np
import pytest
import jax

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                Sgd, DataSet)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.conf.dropout import (Dropout, AlphaDropout,
                                                GaussianDropout, GaussianNoise,
                                                DropConnect, WeightNoise,
                                                MaxNormConstraint,
                                                NonNegativeConstraint,
                                                UnitNormConstraint,
                                                MinMaxNormConstraint)


def _net(layer0_kwargs=None, lr=0.1):
    conf = (NeuralNetConfiguration.builder().seed(3)
            .updater(Sgd(learning_rate=lr)).activation("tanh")
            .list()
            .layer(DenseLayer(n_in=6, n_out=12, **(layer0_kwargs or {})))
            .layer(OutputLayer(n_in=12, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    return MultiLayerNetwork(conf).init()


def _ds(seed=0):
    rng = np.random.default_rng(seed)
    return DataSet(rng.normal(size=(16, 6)).astype(np.float32),
                   np.eye(3, dtype=np.float32)[rng.integers(0, 3, 16)])


# ------------------------------------------------------------ dropout objects
@pytest.mark.parametrize("obj", [Dropout(0.8), AlphaDropout(0.9),
                                 GaussianDropout(0.3), GaussianNoise(0.2)])
def test_dropout_objects_train_vs_inference(obj):
    rng = jax.random.PRNGKey(0)
    x = jax.numpy.ones((64, 32))
    y_train = np.asarray(obj.apply(x, rng, True))
    y_infer = np.asarray(obj.apply(x, None, False))
    np.testing.assert_array_equal(y_infer, np.asarray(x))  # identity at infer
    assert not np.allclose(y_train, np.asarray(x))          # noise at train


def test_dropout_preserves_expectation():
    obj = Dropout(0.5)
    rng = jax.random.PRNGKey(1)
    x = jax.numpy.ones((200, 200))
    y = np.asarray(obj.apply(x, rng, True))
    assert abs(y.mean() - 1.0) < 0.02  # inverted dropout keeps E[x]


def test_network_trains_with_dropout_objects():
    net = _net({"dropout": None})
    net.conf.layers[1].dropout = AlphaDropout(0.9)
    net = MultiLayerNetwork(net.conf).init()
    ds = _ds()
    s0 = net.score(ds)
    for _ in range(10):
        net.fit(ds)
    assert net.score(ds) < s0


# --------------------------------------------------------------- weight noise
def test_dropconnect_changes_training_path_only():
    net = _net({"weight_noise": DropConnect(p=0.7)})
    ds = _ds()
    out1 = np.asarray(net.output(ds.features))
    out2 = np.asarray(net.output(ds.features))
    np.testing.assert_array_equal(out1, out2)  # inference deterministic
    net.fit(ds)  # training applies masking without error
    assert np.isfinite(float(net.score_))


def test_weight_noise_trains():
    net = _net({"weight_noise": WeightNoise(stddev=0.05)})
    ds = _ds()
    s0 = net.score(ds)
    for _ in range(10):
        net.fit(ds)
    assert net.score(ds) < s0


# ---------------------------------------------------------------- constraints
def test_max_norm_constraint_enforced():
    net = _net({"constraints": [MaxNormConstraint(max_norm=0.5)]}, lr=1.0)
    ds = _ds()
    for _ in range(5):
        net.fit(ds)
    W = np.asarray(net.params["0"]["W"])
    col_norms = np.linalg.norm(W, axis=0)
    assert np.all(col_norms <= 0.5 + 1e-5)
    # bias unconstrained by default
    assert "b" in net.params["0"]


def test_non_negative_constraint():
    net = _net({"constraints": [NonNegativeConstraint()]}, lr=0.5)
    ds = _ds()
    for _ in range(3):
        net.fit(ds)
    assert np.all(np.asarray(net.params["0"]["W"]) >= 0.0)


def test_unit_norm_constraint():
    net = _net({"constraints": [UnitNormConstraint()]})
    net.fit(_ds())
    col_norms = np.linalg.norm(np.asarray(net.params["0"]["W"]), axis=0)
    np.testing.assert_allclose(col_norms, 1.0, rtol=1e-5)


def test_min_max_norm_constraint():
    net = _net({"constraints": [MinMaxNormConstraint(min_norm=0.3,
                                                     max_norm=0.6)]}, lr=1.0)
    for _ in range(5):
        net.fit(_ds())
    col_norms = np.linalg.norm(np.asarray(net.params["0"]["W"]), axis=0)
    assert np.all(col_norms <= 0.6 + 1e-5)
    assert np.all(col_norms >= 0.3 - 1e-5)
