"""Model-zoo smoke tests (reference ``deeplearning4j-zoo/src/test/`` pattern:
instantiate + fit a batch per model — SURVEY.md §4 item 7). Full-size ImageNet
configs are built (shape inference + param count); training smoke runs on
reduced inputs where the architecture allows it."""
import numpy as np
import pytest

from deeplearning4j_tpu import DataSet
from deeplearning4j_tpu.models import (ModelSelector, ZOO, LeNet, SimpleCNN,
                                       AlexNet, VGG16, VGG19, GoogLeNet,
                                       ResNet50, InceptionResNetV1,
                                       FaceNetNN4Small2, TextGenerationLSTM)


def _img_batch(n, c, h, w, classes, seed=0):
    rng = np.random.default_rng(seed)
    f = rng.normal(size=(n, c, h, w)).astype(np.float32)
    l = np.eye(classes, dtype=np.float32)[rng.integers(0, classes, n)]
    return DataSet(f, l)


def test_model_selector_knows_all_models():
    assert set(ZOO) == {"lenet", "simplecnn", "alexnet", "vgg16", "vgg19",
                        "googlenet", "resnet50", "inceptionresnetv1",
                        "facenetnn4small2", "textgenlstm", "transformerlm"}
    with pytest.raises(ValueError, match="Unknown zoo model"):
        ModelSelector.select("nope")


def test_lenet_trains():
    net = LeNet(num_classes=10).init()
    assert net.num_params() == 431080  # canonical LeNet-dl4j count
    ds = _img_batch(8, 1, 28, 28, 10)
    s0 = net.score(ds)
    for _ in range(3):
        net.fit(ds)
    assert net.score(ds) < s0


def test_simplecnn_trains():
    net = SimpleCNN(num_classes=5, input_shape=(3, 32, 32)).init()
    ds = _img_batch(4, 3, 32, 32, 5)
    net.fit(ds)
    assert np.isfinite(float(net.score_))
    out = net.output(ds.features)
    np.testing.assert_allclose(np.asarray(out).sum(-1), 1.0, rtol=1e-4)


def test_textgeneration_lstm_trains():
    net = TextGenerationLSTM(total_unique_characters=20).init()
    rng = np.random.default_rng(0)
    T = 12
    f = np.eye(20, dtype=np.float32)[rng.integers(0, 20, (4, T))]
    l = np.eye(20, dtype=np.float32)[rng.integers(0, 20, (4, T))]
    net.fit(DataSet(f, l))
    assert np.isfinite(float(net.score_))


def test_resnet50_canonical_param_count():
    # 25.6M at 1000 classes — matches the torchvision/Keras ResNet50 budget
    net = ResNet50(num_classes=1000, input_shape=(3, 64, 64)).init()
    assert abs(net.num_params() - 25_610_000) / 25_610_000 < 0.01


@pytest.mark.slow
def test_resnet50_trains_small_input():
    # full-ResNet50 XLA compile (~18 s serial CPU) — the two heaviest
    # full-architecture compile smokes ride tier-2 now that the suite
    # presses the serial tier-1 wall budget; conv-family training smoke
    # stays in tier-1 via facenet/inception-resnet/transfer/keras tests
    net = ResNet50(num_classes=4, input_shape=(3, 32, 32)).init()
    ds = _img_batch(4, 3, 32, 32, 4)
    net.fit(ds)
    assert np.isfinite(float(net.score_))


@pytest.mark.slow
def test_googlenet_builds_and_trains():
    """GoogLeNet must FIT inside the smoke window, not just forward — the
    round-3 'first-compile blowup' was ~170 per-shape eager init compiles
    (fixed: host-side numpy init, nn/weights.py::_np_rng); this test pins
    the regression. Slow-marked with resnet50 above (~26 s serial CPU
    compile): the blowup pin is per-shape eager init, which facenet's
    tier-1 fit would regress the same way."""
    net = GoogLeNet(num_classes=6, input_shape=(3, 64, 64)).init()
    ds = _img_batch(4, 3, 64, 64, 6)
    net.fit(ds)
    assert np.isfinite(float(net.score_))
    out = net.output(ds.features)
    assert np.asarray(out).shape == (4, 6)


def test_facenet_center_loss_trains():
    net = FaceNetNN4Small2(num_classes=8, embedding_size=32,
                           input_shape=(3, 32, 32)).init()
    ds = _img_batch(8, 3, 32, 32, 8)
    net.fit(ds)
    assert np.isfinite(float(net.score_))


def test_inception_resnet_v1_builds():
    net = InceptionResNetV1(num_classes=4, input_shape=(3, 64, 64),
                            blocks_a=1, blocks_b=1, blocks_c=1).init()
    out = net.output(_img_batch(2, 3, 64, 64, 4).features)
    assert np.asarray(out).shape == (2, 4)


def test_vgg_and_alexnet_configs_build():
    # full 224×224 configs: shape inference must resolve every nIn
    for cls, expected in ((VGG16, 138_357_544), (VGG19, 143_667_240)):
        conf = cls(num_classes=1000).conf()
        dense = [l for l in conf.layers if type(l).__name__ == "DenseLayer"]
        assert dense[0].n_in == 512 * 7 * 7  # VGG flatten size
        # count params analytically from configs (no init → no 550MB alloc)
    conf = AlexNet(num_classes=1000).conf()
    assert conf.layers[-1].n_in == 4096


def test_pretrained_checksum_workflow(tmp_path, monkeypatch):
    """The reference's download + checksum workflow (ZooModel.java:40-51):
    a filled PRETRAINED_URLS entry is checksum-verified; corrupt files are
    refused; a correct local file round-trips through ModelSerializer."""
    import os
    from deeplearning4j_tpu.models.zoo import LeNet
    from deeplearning4j_tpu.utils.model_serializer import ModelSerializer

    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path))
    m = LeNet(num_classes=10)
    net = m.init()
    path = m.pretrained_path()
    os.makedirs(os.path.dirname(path), exist_ok=True)
    ModelSerializer.write_model(net, path)

    # no registry entry: plain local load works
    restored = m.init_pretrained()
    a = np.asarray(net.params["0"]["W"])
    np.testing.assert_array_equal(a, np.asarray(restored.params["0"]["W"]))

    # registry entry with the CORRECT checksum: verification passes
    good = m._sha256(path)
    monkeypatch.setattr(LeNet, "PRETRAINED_URLS",
                        {"imagenet": ("https://example.invalid/x.bin", good)})
    m.init_pretrained()

    # wrong checksum: local file is refused loudly
    monkeypatch.setattr(LeNet, "PRETRAINED_URLS",
                        {"imagenet": ("https://example.invalid/x.bin",
                                      "0" * 64)})
    with pytest.raises(IOError, match="checksum"):
        m.init_pretrained()

    # missing file + empty registry: actionable error naming the seam
    m2 = LeNet(num_classes=10)
    monkeypatch.setattr(LeNet, "PRETRAINED_URLS", {})
    os.remove(path)
    with pytest.raises(FileNotFoundError, match="PRETRAINED_URLS"):
        m2.init_pretrained()


def test_pretrained_registry_is_per_class():
    """In-place item assignment on one model's registry (the documented
    deployment seam) must not leak to other zoo models (review finding:
    shared base-class dict)."""
    from deeplearning4j_tpu.models.zoo import LeNet, AlexNet, ZooModel
    LeNet.PRETRAINED_URLS["imagenet"] = ("https://example.invalid/l.bin",
                                         "a" * 64)
    try:
        assert "imagenet" not in AlexNet.PRETRAINED_URLS
        assert "imagenet" not in ZooModel.PRETRAINED_URLS
    finally:
        LeNet.PRETRAINED_URLS.pop("imagenet", None)


def test_transformer_lm_trains_and_streams():
    """TransformerLM (net-new flagship): pre-LN residual CG builds, trains
    on a toy char task, and the causal structure holds — streaming
    rnn_time_step equals the full causal forward."""
    import jax
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu import DataSet

    m = TransformerLM(vocab_size=12, embed_dim=32, num_heads=2,
                      num_blocks=2, seed=7)
    net = m.init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 12, size=(4, 16))
    labels = np.eye(12, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet
    mds = MultiDataSet((ids.astype(np.float32),), (labels,))
    s0 = float(net.score(mds))
    for _ in range(8):
        net.fit(mds)
    assert float(net.score(mds)) < s0

    # causal check: future tokens cannot change earlier outputs
    # (single-output CG: output() returns the [b, T, V] array directly)
    out_a = np.asarray(net.output(ids.astype(np.float32)))
    ids_b = ids.copy()
    ids_b[:, -1] = (ids_b[:, -1] + 1) % 12
    out_b = np.asarray(net.output(ids_b.astype(np.float32)))
    assert out_a.shape == (4, 16, 12)
    np.testing.assert_allclose(out_a[:, :-1], out_b[:, :-1],
                               rtol=1e-5, atol=1e-6)
    assert np.abs(out_a[:, -1] - out_b[:, -1]).max() > 1e-4


@pytest.mark.slow
def test_transformer_lm_moe_trains_and_ep_shards():
    """num_experts > 0 turns every block FFN into a sparse MoE; the model
    trains, and the expert dim shards over an `expert` mesh via
    expert_parallel_step (the ep axis on the flagship)."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import TransformerLM
    from deeplearning4j_tpu.parallel import make_mesh
    from deeplearning4j_tpu.parallel.expert import (EXPERT_AXIS,
                                                    expert_parallel_step)
    from deeplearning4j_tpu.datasets.dataset import MultiDataSet

    m = TransformerLM(vocab_size=10, embed_dim=16, num_heads=2,
                      num_blocks=2, num_experts=4, top_k=2,
                      capacity_factor=2.0, seed=11)
    net = m.init()
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 10, size=(4, 8))
    labels = np.eye(10, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    mds = MultiDataSet((ids.astype(np.float32),), (labels,))
    s0 = float(net.score(mds))
    for _ in range(6):
        net.fit(mds)
    assert float(net.score(mds)) < s0

    # ep: experts sharded over 4 devices, one jitted step runs
    net2 = TransformerLM(vocab_size=10, embed_dim=16, num_heads=2,
                         num_blocks=2, num_experts=4, top_k=2,
                         capacity_factor=2.0, seed=11).init()
    mesh = make_mesh(jax.devices()[:4], axes=(EXPERT_AXIS,))
    step, place = expert_parallel_step(net2, mesh)
    place(net2)
    _, _, _, loss = step(net2.params, net2.states, net2.updater_state,
                         jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
                         (jnp.asarray(ids, jnp.float32),),
                         (jnp.asarray(labels),), None, None)
    assert np.isfinite(float(loss))


def test_transformer_lm_rnn_time_step_matches_full():
    """Token-by-token generation through the KV cache (CG rnn_time_step)
    reproduces the full causal forward — the streaming-inference contract
    on the flagship model."""
    from deeplearning4j_tpu.models import TransformerLM

    net = TransformerLM(vocab_size=9, embed_dim=16, num_heads=2,
                        num_blocks=2, seed=13).init()
    rng = np.random.default_rng(2)
    T = 7
    ids = rng.integers(0, 9, size=(2, T)).astype(np.float32)
    full = np.asarray(net.output(ids))

    net.rnn_clear_previous_state()
    stepped = []
    for t in range(T):
        # [b, 1] single-token step -> [b, V] (the single-step convention)
        y = np.asarray(net.rnn_time_step(ids[:, t:t + 1]))
        assert y.shape == (2, 9)
        stepped.append(y)
    stepped = np.stack(stepped, axis=1)
    np.testing.assert_allclose(stepped, full, rtol=2e-4, atol=2e-5)


def test_generate_tokens_both_model_families():
    """generate_tokens (the reference TextGenerationLSTM sampling workflow)
    drives BOTH streaming stacks: TransformerLM via the KV cache (id
    inputs) and TextGenerationLSTM via recurrent state (one-hot inputs) —
    deterministic per seed, near-greedy at tiny temperature."""
    from deeplearning4j_tpu.models import (TransformerLM, TextGenerationLSTM,
                                           generate_tokens)
    from deeplearning4j_tpu import MultiLayerNetwork

    tf_net = TransformerLM(vocab_size=9, embed_dim=16, num_heads=2,
                           num_blocks=2, seed=2).init()
    prompt = np.array([[1, 2, 3], [4, 5, 6]])
    a = generate_tokens(tf_net, prompt, 5, seed=7)
    b = generate_tokens(tf_net, prompt, 5, seed=7)
    c = generate_tokens(tf_net, prompt, 5, seed=8)
    assert a.shape == (2, 5) and (0 <= a).all() and (a < 9).all()
    np.testing.assert_array_equal(a, b)          # deterministic per seed
    assert (a != c).any()                        # seed-sensitive

    # near-greedy: tiny temperature == argmax of streaming probs
    g1 = generate_tokens(tf_net, prompt, 4, temperature=1e-4, seed=1)
    g2 = generate_tokens(tf_net, prompt, 4, temperature=1e-4, seed=99)
    np.testing.assert_array_equal(g1, g2)

    lstm_net = MultiLayerNetwork(
        TextGenerationLSTM(total_unique_characters=9, lstm_size=16).conf()
    ).init()
    d = generate_tokens(lstm_net, prompt, 5, seed=7)
    assert d.shape == (2, 5) and (0 <= d).all() and (d < 9).all()
    np.testing.assert_array_equal(d, generate_tokens(lstm_net, prompt, 5,
                                                     seed=7))


def test_generate_tokens_degenerate_sizes():
    from deeplearning4j_tpu.models import TransformerLM, generate_tokens

    net = TransformerLM(vocab_size=7, embed_dim=16, num_heads=2,
                        num_blocks=2, seed=4).init()
    with pytest.raises(ValueError, match="non-empty prompt"):
        generate_tokens(net, np.zeros((2, 0)), 4)
    out = generate_tokens(net, np.array([[1, 2]]), 0)
    assert out.shape == (1, 0)


def test_generate_tokens_advances_state_past_last_token():
    """After generate_tokens (default advance_state=True), continuing with
    rnn_time_step must condition on the FULL returned sequence — identical
    to streaming prompt+generated through a fresh state (review finding:
    skipping the final step left the cache one token behind)."""
    from deeplearning4j_tpu.models import TransformerLM, generate_tokens

    net = TransformerLM(vocab_size=9, embed_dim=16, num_heads=2,
                        num_blocks=2, seed=3).init()
    prompt = np.array([[1, 2, 3]])
    gen = generate_tokens(net, prompt, 4, seed=11)

    probe = np.array([[2.0]])                        # next streamed token
    cont = np.asarray(net.rnn_time_step(probe))      # uses post-gen state

    net.rnn_clear_previous_state()
    full = np.concatenate([prompt, gen], axis=1).astype(np.float32)
    net.rnn_time_step(full[:, :, None])              # replay whole history
    want = np.asarray(net.rnn_time_step(probe))
    np.testing.assert_allclose(cont, want, rtol=1e-4, atol=1e-5)


def test_pretrained_download_workflow_file_url(tmp_path, monkeypatch):
    """The ACTUAL download path of the reference workflow (ZooModel.java:
    40-51), end-to-end under zero egress via a file:// URL: real (trained)
    weights are served from a 'remote' dir, fetched into the zoo data dir,
    sha256-verified, restored, and predict matches the original model.
    The corrupt-download path must delete the .part and leave no weights
    behind."""
    import os
    import urllib.request
    from deeplearning4j_tpu.models.zoo import LeNet, TextGenerationLSTM
    from deeplearning4j_tpu.utils.model_serializer import ModelSerializer
    from deeplearning4j_tpu.datasets.dataset import DataSet

    rng = np.random.default_rng(0)

    # --- produce REAL weights: train LeNet a few steps off random init
    m = LeNet(num_classes=10)
    net = m.init()
    x = rng.normal(size=(32, 1, 28, 28)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, 32)]
    for _ in range(3):
        net.fit(DataSet(x, y))
    want = net.output(x)

    server = tmp_path / "server"
    server.mkdir()
    served = server / "lenet_imagenet.bin"
    ModelSerializer.write_model(net, str(served))
    sha = m._sha256(str(served))
    url = "file://" + urllib.request.pathname2url(str(served))

    # --- client side: empty data dir, registry filled → download happens
    client = tmp_path / "client"
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(client))
    monkeypatch.setattr(LeNet, "PRETRAINED_URLS", {"imagenet": (url, sha)})
    assert not os.path.exists(m.pretrained_path())
    restored = m.init_pretrained()
    assert os.path.exists(m.pretrained_path())      # fetched into the zoo dir
    np.testing.assert_allclose(np.asarray(restored.output(x)),
                               np.asarray(want), rtol=1e-5, atol=1e-6)

    # --- corrupt download: wrong sha refuses, cleans up, leaves nothing
    m2 = LeNet(num_classes=10)
    monkeypatch.setenv("DL4J_TPU_DATA_DIR", str(tmp_path / "client2"))
    monkeypatch.setattr(LeNet, "PRETRAINED_URLS",
                        {"imagenet": (url, "0" * 64)})
    with pytest.raises(IOError, match="[Cc]hecksum"):
        m2.init_pretrained()
    assert not os.path.exists(m2.pretrained_path())
    assert not os.path.exists(m2.pretrained_path() + ".part")

    # --- TextGenerationLSTM through the same wire
    tg = TextGenerationLSTM(total_unique_characters=12, lstm_size=16)
    tnet = tg.init()
    seq = np.eye(12, dtype=np.float32)[
        rng.integers(0, 12, size=(4, 20))].astype(np.float32)
    lab = np.eye(12, dtype=np.float32)[
        rng.integers(0, 12, size=(4, 20))].astype(np.float32)
    tnet.fit(DataSet(seq, lab))
    twant = tnet.output(seq)
    tserved = server / "textgen.bin"
    ModelSerializer.write_model(tnet, str(tserved))
    turl = "file://" + urllib.request.pathname2url(str(tserved))
    monkeypatch.setattr(TextGenerationLSTM, "PRETRAINED_URLS",
                        {"imagenet": (turl, tg._sha256(str(tserved)))})
    trestored = tg.init_pretrained()
    np.testing.assert_allclose(np.asarray(trestored.output(seq)),
                               np.asarray(twant), rtol=1e-5, atol=1e-6)
