"""Probe plane (docs/OBSERVABILITY.md "Probe plane"): golden-set
capture through the real serving path, probe traffic's end-to-end
response-cache bypass, the black-box :class:`Prober` daemon with its
ok/error/timeout/mismatch SLIs and deadman gauge, the
``default_probe_rules`` pack, ``GET /probes`` on both server families,
and THE gray-failure acceptance drill — a real replica subprocess that
keeps self-reporting healthy while serving WRONG answers is detected
only by probes, named in a firing alert carrying a trace id resolvable
on that replica, auto-restarted by ``probe_failure_policy``, and the
whole incident reconstructs from ``/events``.
"""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from deeplearning4j_tpu.control import ControlPlane, probe_failure_policy
from deeplearning4j_tpu.monitor import (ProbeTarget, Prober,
                                        default_probe_rules)
from deeplearning4j_tpu.monitor.flightrec import get_flight_recorder
from deeplearning4j_tpu.monitor.health import get_health
from deeplearning4j_tpu.monitor.tracer import get_tracer
from deeplearning4j_tpu.serving import (InferenceServer, ModelRegistry,
                                        PROBE_HEADER, TRACE_HEADER)
from deeplearning4j_tpu.ui import InMemoryStatsStorage, UIServer


def _get_json(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10) as r:
            return r.status, json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        body = json.loads(e.read().decode("utf-8"))
        e.close()
        return e.code, body


def _post_predict(port, inputs, model="drill", headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/models/{model}/predict",
        data=json.dumps({"inputs": inputs}).encode("utf-8"),
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=30) as resp:
            return resp.status, json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        body = json.loads(e.read().decode("utf-8"))
        e.close()
        return e.code, body


class DoubleModel:
    """Deterministic duck-typed model: first two columns, doubled.
    ``bias`` is mutable so tests can flip the LIVE path's answer after
    an entry is cached — the only way to tell a cache read from a real
    forward."""

    def __init__(self, bias=0.0):
        self.bias = bias

    def output(self, x, mask=None):
        return np.asarray(x, np.float32)[:, :2] * 2.0 + self.bias


# ----------------------------------------------------- golden capture
class TestGolden:
    def test_golden_is_deterministic_version_keyed_and_latched(self):
        """Two captures of the same weights produce the same version
        (the canonical inputs are deterministic); the capture is latched
        (same object back) and surfaces in stats(); refresh re-captures."""
        reg = ModelRegistry()
        reg.register("g", DoubleModel(), input_shape=(4,),
                     batch_buckets=(1, 2), linger_ms=0.0)
        try:
            m = reg.get("g")
            g1 = m.golden()
            assert g1["model"] == "g" and g1["precision"] == "f32"
            assert g1["atol"] == pytest.approx(1e-4)
            x = np.asarray(g1["inputs"], np.float32)
            np.testing.assert_allclose(
                np.asarray(g1["outputs"], np.float32), x[:, :2] * 2.0)
            assert m.golden() is g1            # latched
            assert m.stats()["golden_version"] == g1["version"]
            g2 = m.golden(refresh=True)
            assert g2 is not g1
            assert g2["version"] == g1["version"]   # same weights
        finally:
            reg.close_all()

    def test_golden_needs_input_shape_or_explicit_inputs(self):
        reg = ModelRegistry()
        reg.register("noshape", DoubleModel(), batch_buckets=(1, 2),
                     linger_ms=0.0)
        try:
            m = reg.get("noshape")
            with pytest.raises(ValueError, match="input_shape"):
                m.golden()
            g = m.golden(inputs=[[1.0, 2.0, 3.0, 4.0]])
            np.testing.assert_allclose(
                np.asarray(g["outputs"], np.float32), [[2.0, 4.0]])
        finally:
            reg.close_all()

    def test_golden_capture_bypasses_the_response_cache(self):
        """The oracle must describe the live model path: capturing a
        golden set on a cache-enabled model stores NOTHING in the LRU."""
        reg = ModelRegistry()
        reg.register("gc", DoubleModel(), input_shape=(4,),
                     batch_buckets=(1, 2), linger_ms=0.0, cache_size=16)
        try:
            m = reg.get("gc")
            m.golden()
            assert m.stats()["cache"]["entries"] == 0
        finally:
            reg.close_all()

    def test_bf16_golden_gets_loose_atol(self):
        reg = ModelRegistry()
        reg.register("gb", DoubleModel(), input_shape=(4,),
                     batch_buckets=(1, 2), linger_ms=0.0,
                     precision="bf16")
        try:
            assert reg.get("gb").golden()["atol"] == pytest.approx(5e-2)
        finally:
            reg.close_all()


# ------------------------------------------------- cache-bypass pins
class TestProbeCacheBypass:
    def test_submit_cache_bypass_neither_reads_nor_populates(self):
        """Direct-submit pin: ``cache_bypass=True`` requests keep
        ``ckey=None`` end to end — no lookup (a stale cached answer
        cannot mask the live path) and no store (probes never evict
        real traffic's entries)."""
        reg = ModelRegistry()
        model = DoubleModel()
        reg.register("cb", model, input_shape=(4,),
                     batch_buckets=(1, 2), linger_ms=0.0, cache_size=16)
        try:
            m = reg.get("cb")
            x = [[1.0, 2.0, 3.0, 4.0]]
            # bypass submits never populate
            m.predict(x, cache_bypass=True)
            m.predict(x, cache_bypass=True)
            assert m.stats()["cache"]["entries"] == 0
            # a normal request populates with the CORRECT answer ...
            np.testing.assert_allclose(
                np.asarray(m.predict(x), np.float32), [[2.0, 4.0]])
            assert m.stats()["cache"]["entries"] == 1
            # ... then the live path goes wrong: a bypass request must
            # see the wrong LIVE answer (no read), a normal request the
            # cached right one (the LRU still serves real traffic)
            model.bias = 100.0
            np.testing.assert_allclose(
                np.asarray(m.predict(x, cache_bypass=True), np.float32),
                [[102.0, 104.0]])
            np.testing.assert_allclose(
                np.asarray(m.predict(x), np.float32), [[2.0, 4.0]])
            assert m.stats()["cache"]["entries"] == 1   # no new entry
        finally:
            reg.close_all()

    def test_probe_header_bypasses_cache_over_http(self):
        """Wire-level pin: ``X-DL4J-Probe: 1`` rides the header to
        ``cache_bypass`` — probe POSTs leave the LRU empty, an identical
        normal POST populates it, and a subsequent probe POST still
        reaches the live model rather than the cached entry."""
        srv = InferenceServer()
        model = DoubleModel()
        srv.register("h", model, input_shape=(4,),
                     batch_buckets=(1, 2), linger_ms=0.0, cache_size=16)
        port = srv.start(port=0)
        x = [[1.0, 2.0, 3.0, 4.0]]
        try:
            m = srv.registry.get("h")
            for _ in range(2):
                status, doc = _post_predict(port, x, model="h",
                                            headers={PROBE_HEADER: "1"})
                assert status == 200
                assert doc["outputs"] == [[2.0, 4.0]]
            assert m.stats()["cache"]["entries"] == 0
            status, _ = _post_predict(port, x, model="h")
            assert status == 200
            assert m.stats()["cache"]["entries"] == 1
            # wedge the live path: a probe POST must see the wrong LIVE
            # answer through the cached-right-answer trap, a normal POST
            # the cached entry
            model.bias = 100.0
            status, doc = _post_predict(port, x, model="h",
                                        headers={PROBE_HEADER: "1"})
            assert status == 200
            assert doc["outputs"] == [[102.0, 104.0]]
            status, doc = _post_predict(port, x, model="h")
            assert status == 200
            assert doc["outputs"] == [[2.0, 4.0]]
            assert m.stats()["cache"]["entries"] == 1
        finally:
            srv.stop()


# ------------------------------------------------- prober unit tests
class TestProbeTarget:
    def test_url_normalization_and_golden_validation(self):
        g = {"model": "m", "inputs": [[1.0]], "outputs": [[2.0]],
             "atol": 1e-3, "version": "abc"}
        t = ProbeTarget("r0", "127.0.0.1:8500/", g)
        assert t.url == "http://127.0.0.1:8500"
        assert t.model == "m" and t.atol == pytest.approx(1e-3)
        with pytest.raises(ValueError, match="golden"):
            ProbeTarget("bad", "127.0.0.1:1", {"inputs": [[1.0]]})
        with pytest.raises(ValueError, match="model"):
            ProbeTarget("bad", "127.0.0.1:1",
                        {"inputs": [[1.0]], "outputs": [[1.0]]})


class TestProberTick:
    def test_ok_probe_lands_slis_and_resolvable_trace(self):
        """A healthy target probes ``ok``: the request counter, the
        client-side latency histogram (probe trace id latched as its
        exemplar) and a ~0 deadman land; the probe's minted trace id is
        resolvable in the replica's trace ring; the LRU stays empty."""
        srv = InferenceServer()
        m = srv.register("ok", DoubleModel(), input_shape=(4,),
                         batch_buckets=(1, 2), linger_ms=0.0,
                         cache_size=8)
        port = srv.start(port=0)
        p = Prober()
        try:
            p.add_target("u_ok", f"127.0.0.1:{port}", m.golden())
            t0 = time.time()
            res = p.tick(now=t0)
            assert res["probed"] == ["u_ok"]
            assert res["outcomes"] == {"u_ok": "ok"} and not res["errors"]
            snap = p.snapshot()["targets"]["u_ok"]
            assert snap["last_outcome"] == "ok"
            assert snap["consecutive_failures"] == 0
            assert snap["golden_version"] == m.golden()["version"]
            # the probe's trace id joined the replica's /trace (the
            # in-process server shares this tracer)
            assert snap["last_trace_id"] in {
                (e.get("args") or {}).get("trace_id")
                for e in get_tracer().export()["traceEvents"]}
            # SLIs: counter child + deadman ~0 in the prober's dump
            dump = p.probe_dump()
            oks = [r["value"]
                   for r in dump["probe_requests_total"]["children"]
                   if r["labels"] == {"target": "u_ok", "model": "ok",
                                      "outcome": "ok"}]
            assert oks and oks[0] >= 1
            ages = [r["value"]
                    for r in dump["probe_last_success_age_s"]["children"]
                    if r["labels"]["target"] == "u_ok"]
            assert ages == [0.0]
            assert m.stats()["cache"]["entries"] == 0
            # one history sample + engine pass per tick (the upward loop)
            assert len(p.history.samples()) == 1
        finally:
            p.remove_target("u_ok")
            srv.stop()
            get_tracer().clear()

    def test_mismatch_outcome_holds_the_deadman_and_edges_once(self):
        """A replica answering quickly but WRONGLY is a mismatch: the
        deadman keeps growing (only a correct answer resets it), the
        failing flight event fires exactly once on the edge, sustained
        failure lands ONE health_problem(kind=probe), and recovery
        (fixed golden) emits the recovered edge."""
        srv = InferenceServer()
        m = srv.register("wrong", DoubleModel(), input_shape=(4,),
                         batch_buckets=(1, 2), linger_ms=0.0)
        port = srv.start(port=0)
        rec = get_flight_recorder()
        good = m.golden()
        bad = dict(good)
        bad["outputs"] = (np.asarray(good["outputs"], np.float32)
                          + 5.0).tolist()
        p = Prober(fail_threshold=2)
        try:
            before = len([e for e in rec.events()
                          if e["event"] == "health_problem"
                          and e.get("kind") == "probe"])
            p.add_target("u_mm", f"127.0.0.1:{port}", bad)
            t0 = time.time()
            for k in range(3):
                res = p.tick(now=t0 + k)
                assert res["outcomes"] == {"u_mm": "mismatch"}
            snap = p.snapshot()["targets"]["u_mm"]
            assert snap["consecutive_failures"] == 3
            assert [t.label for t in p.failing_targets()] == ["u_mm"]
            # deadman grew across the synthetic beats in the sampled ring
            ages = p.history.series("probe_last_success_age_s")
            vals = [pt["value"] for pt in ages["points"]]
            assert max(vals) >= 2.0
            fails = [e for e in rec.events()
                     if e["event"] == "probe_target_failing"
                     and e.get("target") == "u_mm"]
            assert len(fails) == 1              # edge, not per-tick
            assert fails[0]["outcome"] == "mismatch"
            assert fails[0].get("trace_id")
            probs = [e for e in rec.events()
                     if e["event"] == "health_problem"
                     and e.get("kind") == "probe"]
            assert len(probs) - before == 1     # once per incident
            assert "u_mm" in probs[-1]["message"]
            assert any(pr.startswith("probe:")
                       for pr in get_health().snapshot()["problems"])
            # fix the oracle: recovery edge + deadman reset
            p.add_target("u_mm", f"127.0.0.1:{port}", good)
            res = p.tick(now=t0 + 3)
            assert res["outcomes"] == {"u_mm": "ok"}
            assert any(e["event"] == "probe_target_recovered"
                       and e.get("target") == "u_mm"
                       for e in rec.events())
            assert p.snapshot()["targets"]["u_mm"][
                "consecutive_failures"] == 0
        finally:
            p.remove_target("u_mm")
            srv.stop()
            get_tracer().clear()

    def test_down_target_is_an_error_and_removal_retires_series(self):
        g = {"model": "m", "inputs": [[1.0]], "outputs": [[1.0]]}
        p = Prober(timeout_s=0.2, fail_threshold=99)
        p.add_target("u_gone", "127.0.0.1:9", g)     # refused
        res = p.tick(now=time.time())
        assert res["outcomes"] == {"u_gone": "error"}
        assert "u_gone" in res["errors"]
        assert [t.label for t in p.failing_targets()] == ["u_gone"]
        assert any(r["labels"]["target"] == "u_gone"
                   for r in p.probe_dump()
                   ["probe_last_success_age_s"]["children"])
        p.remove_target("u_gone")
        fam = p.probe_dump().get("probe_last_success_age_s")
        assert not fam or not [
            r for r in fam.get("children", [])
            if r["labels"]["target"] == "u_gone"]

    def test_lifecycle_start_is_idempotent_and_stop_joins(self):
        p = Prober()
        p.start(interval_s=120.0)
        try:
            assert p.running()
            assert "prober" in [t.name for t in threading.enumerate()]
            p.start()                        # idempotent
            assert p.snapshot()["running"] is True
        finally:
            p.stop()
        assert not p.running()
        assert "prober" not in [t.name for t in threading.enumerate()]


# ---------------------------------------- endpoint + default rules
class TestProbesEndpoint:
    def test_get_probes_served_on_both_server_families(self):
        """The shared ``_monitor_get`` serves ``/probes`` on the training
        UI server AND the serving front door — same payload shape."""
        ui = UIServer(port=0)
        ui.attach(InMemoryStatsStorage())
        ui_port = ui.start()
        srv = InferenceServer()
        srv_port = srv.start(port=0)
        try:
            for port in (ui_port, srv_port):
                status, doc = _get_json(port, "/probes")
                assert status == 200
                for key in ("interval_s", "fail_threshold", "running",
                            "targets"):
                    assert key in doc
        finally:
            ui.stop()
            srv.stop()


class TestDefaultProbeRules:
    def test_pack_names_and_prober_wired_annotations(self):
        rules = default_probe_rules()
        assert [r.name for r in rules] == [
            "probe_availability_burn", "probe_p99_client",
            "probe_mismatch", "probe_deadman"]
        burn = rules[0]
        assert {"outcome": "mismatch"} in burn.bad_labels
        p = Prober()
        wired = default_probe_rules(p)
        assert wired[2].exemplar_lookup == p.last_failure_trace
        assert wired[3].detail_lookup == p.failure_detail

    def test_mismatch_rule_fires_with_guilty_detail_and_exemplar(self):
        """In-process walk of the pack: a wrong golden drives
        ``probe_mismatch`` and ``probe_deadman`` to FIRING with the
        failing target named via ``detail_lookup`` and the probe's own
        trace id as the exemplar; fixing the oracle resolves both."""
        srv = InferenceServer()
        m = srv.register("rw", DoubleModel(), input_shape=(4,),
                         batch_buckets=(1, 2), linger_ms=0.0)
        port = srv.start(port=0)
        good = m.golden()
        bad = dict(good)
        bad["outputs"] = (np.asarray(good["outputs"], np.float32)
                          + 9.0).tolist()
        p = Prober(fail_threshold=99)
        p.engine.add(*default_probe_rules(
            p, windows=(1.5, 3.0), deadman_s=2.0, for_seconds=0.2))
        edges = []
        p.engine.subscribe(lambda ev, pl: edges.append((ev, dict(pl))))
        try:
            p.add_target("u_rule", f"127.0.0.1:{port}", good)
            t0 = time.time()
            step = 0
            for _ in range(7):               # healthy: cover the windows
                step += 1
                p.tick(now=t0 + 0.5 * step)
            states = {r.name: r.state for r in p.engine.rules()}
            assert set(states.values()) == {"OK"}, states
            p.add_target("u_rule", f"127.0.0.1:{port}", bad)
            for _ in range(14):
                step += 1
                p.tick(now=t0 + 0.5 * step)
                states = {r.name: r.state for r in p.engine.rules()}
                if (states["probe_mismatch"] == "FIRING"
                        and states["probe_deadman"] == "FIRING"):
                    break
            assert states["probe_mismatch"] == "FIRING", \
                [(r.name, r.state, r.last_detail)
                 for r in p.engine.rules()]
            assert states["probe_deadman"] == "FIRING"
            fired = [pl for ev, pl in edges if ev == "alert_firing"
                     and pl.get("rule") == "probe_mismatch"]
            assert fired
            assert "u_rule" in fired[-1]["detail"]
            exemplar = fired[-1].get("exemplar_trace_id")
            assert exemplar
            assert exemplar in {
                (e.get("args") or {}).get("trace_id")
                for e in get_tracer().export()["traceEvents"]}
            p.add_target("u_rule", f"127.0.0.1:{port}", good)
            for _ in range(16):
                step += 1
                p.tick(now=t0 + 0.5 * step)
                states = {r.name: r.state for r in p.engine.rules()}
                if set(states.values()) == {"OK"}:
                    break
            assert set(states.values()) == {"OK"}, \
                [(r.name, r.state, r.last_detail)
                 for r in p.engine.rules()]
            assert {pl.get("rule") for ev, pl in edges
                    if ev == "alert_resolved"} >= {"probe_mismatch",
                                                   "probe_deadman"}
        finally:
            p.engine.clear()
            p.remove_target("u_rule")
            srv.stop()
            get_tracer().clear()


# ------------------------------------------ THE gray-failure drill
# One replica subprocess: a model whose answers go WRONG (but stay fast
# and 200) when the flag file exists — /telemetry and /healthz keep
# self-reporting healthy, which is exactly the failure no push/scrape
# signal can see. Prints one JSON line {"port": ..., "golden": ...}
# (the golden set captured at registration, pre-fault), then blocks on
# stdin so kill/terminate is the drill's process control.
_REPLICA_SRC = r"""
import json, os, sys
import numpy as np

flag = sys.argv[1]

class GrayModel:
    def output(self, x, mask=None):
        out = np.asarray(x, np.float32)[:, :2] * 2.0
        if os.path.exists(flag):       # gray failure: fast, 200, WRONG
            out = out + 37.0
        return out

from deeplearning4j_tpu.serving import InferenceServer

srv = InferenceServer()
served = srv.register("drill", GrayModel(), input_shape=(4,),
                      batch_buckets=(1, 2), linger_ms=0.0,
                      max_queue_examples=64, cache_size=16)
golden = served.golden()
port = srv.start(port=0)
print(json.dumps({"port": port, "golden": golden}), flush=True)
sys.stdin.read()
"""


def _spawn_replica(flag_path, err_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"      # numpy model; never wait on a device
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    errf = open(err_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-c", _REPLICA_SRC, str(flag_path)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=errf,
        text=True, env=env, cwd=root)
    box = {}

    def _read():
        box["line"] = proc.stdout.readline()

    t = threading.Thread(target=_read, daemon=True)
    t.start()
    t.join(120)
    line = (box.get("line") or "").strip()
    if not line:
        proc.kill()
        proc.wait(timeout=30)
        errf.close()
        with open(err_path) as f:
            raise RuntimeError(f"replica failed to start:\n{f.read()}")
    errf.close()
    doc = json.loads(line)
    return proc, int(doc["port"]), doc["golden"]


class TestGrayFailureDrill:
    def test_gray_failure_detected_probed_restarted_reconstructed(
            self, tmp_path):
        """THE acceptance scenario, end to end: two real replica
        processes probed by a live Prober. Wedging r1's model (wrong
        answers, still fast, still 200) leaves every self-reported
        surface green — r1's own ``/healthz`` says healthy and its
        ``/telemetry`` keeps answering — while ``probe_mismatch`` and
        ``probe_deadman`` walk OK→PENDING→FIRING naming r1 with a probe
        trace id resolvable on r1's own ``/trace``;
        ``probe_failure_policy`` restarts r1 at fire time; steady state
        returns alert-free with a ``probe_target_recovered`` edge; the
        whole incident reads back off ``/events``; and no probe ever
        lands in any response cache."""
        rec = get_flight_recorder()
        rec.clear()
        ui = UIServer(port=0)
        ui.attach(InMemoryStatsStorage())
        ui_port = ui.start()
        flag = tmp_path / "gray_r1"
        prober = Prober(timeout_s=10.0, fail_threshold=3)
        edges = []
        prober.engine.subscribe(
            lambda ev, payload: edges.append((ev, dict(payload))))
        prober.engine.add(*default_probe_rules(
            prober, windows=(1.5, 3.0), deadman_s=2.0, for_seconds=0.2))
        plane = ControlPlane(engine=prober.engine)
        procs = []
        restarted = []
        box = {}                             # live r1 port for asserts

        def restart_replica(label, url):
            """The drill's actuator: bounce the wedged replica — kill,
            clear the fault, respawn, re-register the probe target with
            the NEW process's own golden set."""
            restarted.append(label)
            old = box.pop("proc")
            old.kill()
            old.wait(timeout=30)
            if flag.exists():
                flag.unlink()
            p1b, port1b, golden1b = _spawn_replica(
                flag, tmp_path / "r1b.err")
            procs.append(p1b)
            box.update(proc=p1b, port=port1b, golden=golden1b)
            prober.add_target(label, f"127.0.0.1:{port1b}", golden1b)

        plane.add(probe_failure_policy(prober, restart_replica,
                                       cooldown_s=60.0))
        prober.engine.subscribe(plane._on_edge)
        states = []
        step = [0]

        def beat(drive_plane=True):
            # synthetic clock: one beat = 0.5s. The plane's tick is
            # held back during the wedge (drive_plane=False) so the
            # drill can watch BOTH rules reach FIRING before the
            # remediation kicks in — a real deployment's plane cadence
            # simply lagging the prober's.
            step[0] += 1
            now = t0 + 0.5 * step[0]
            res = prober.tick(now=now)
            if drive_plane:
                plane.tick(now=now)
            states.append({r.name: r.state
                           for r in prober.engine.rules()})
            return res

        try:
            p0, port0, golden0 = _spawn_replica(tmp_path / "no_fault_r0",
                                                tmp_path / "r0.err")
            procs.append(p0)
            p1, port1, golden1 = _spawn_replica(flag, tmp_path / "r1.err")
            procs.append(p1)
            box.update(proc=p1, port=port1, golden=golden1)
            prober.add_target("r0", f"127.0.0.1:{port0}", golden0)
            prober.add_target("r1", f"127.0.0.1:{port1}", golden1)

            # live prober: start() probes immediately (interval far
            # beyond the drill so the deterministic beats own the clock)
            prober.start(interval_s=120.0)
            assert prober.running()
            assert "prober" in [t.name for t in threading.enumerate()]
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                targets = prober.snapshot()["targets"]
                if len(targets) == 2 and all(
                        v["last_outcome"] == "ok"
                        for v in targets.values()):
                    break
                time.sleep(0.05)
            else:
                pytest.fail(f"live probe never landed: "
                            f"{prober.snapshot()}")
            time.sleep(0.25)          # let the first tick's sample+eval
            t0 = time.time()          # finish before synthetic beats

            # ---- healthy baseline: windows covered, everything OK
            for _ in range(7):
                res = beat()
                assert res["outcomes"] == {"r0": "ok", "r1": "ok"}, res
            assert set(states[-1].values()) == {"OK"}, states[-1]

            # seed r1's cache with the CORRECT answer for the golden
            # inputs via a normal request — the poisoned-cache trap: a
            # cache-reading probe would keep seeing this right answer
            # straight through the wedge
            status, _ = _post_predict(port1, golden1["inputs"])
            assert status == 200
            status, stats = _get_json(port1, "/v1/models/drill")
            assert status == 200 and stats["cache"]["entries"] == 1
            assert stats["golden_version"] == golden1["version"]

            # ---- wedge r1: answers go wrong, everything self-reported
            # stays green
            flag.write_text("x")
            status, cached = _post_predict(port1, golden1["inputs"])
            assert status == 200     # normal traffic: cached RIGHT answer
            np.testing.assert_allclose(
                np.asarray(cached["outputs"], np.float32),
                np.asarray(golden1["outputs"], np.float32),
                atol=float(golden1["atol"]))
            for _ in range(18):
                beat(drive_plane=False)
                if (states[-1]["probe_mismatch"] == "FIRING"
                        and states[-1]["probe_deadman"] == "FIRING"):
                    break
                # the gray failure is invisible to self-report while the
                # probes close in on it
                status, h = _get_json(box["port"], "/healthz")
                assert status == 200 and h["healthy"] is True
                status, _ = _get_json(box["port"], "/telemetry")
                assert status == 200
            assert states[-1]["probe_mismatch"] == "FIRING", \
                [(r.name, r.state, r.last_detail)
                 for r in prober.engine.rules()]
            assert states[-1]["probe_deadman"] == "FIRING"
            walk = [s["probe_mismatch"] for s in states]
            assert "PENDING" in walk, walk       # hold-down honored

            # the firing edge names the GUILTY replica and carries the
            # probe's own trace id, resolvable on THAT replica's /trace
            fired = [p for ev, p in edges if ev == "alert_firing"
                     and p.get("rule") == "probe_mismatch"]
            assert fired, edges
            assert "r1" in (fired[-1].get("detail") or "")
            exemplar = fired[-1].get("exemplar_trace_id")
            assert exemplar
            status, rtrace = _get_json(port1, "/trace")
            assert status == 200
            assert exemplar in {
                (e.get("args") or {}).get("trace_id")
                for e in rtrace["traceEvents"]}

            # sustained failure landed on the PROBER's /healthz as a
            # timestamped problem (kind=probe), and the failing edge hit
            # the flight recorder exactly once
            assert any(e["event"] == "health_problem"
                       and e.get("kind") == "probe"
                       and "r1" in e.get("message", "")
                       for e in rec.events())
            assert len([e for e in rec.events()
                        if e["event"] == "probe_target_failing"
                        and e.get("target") == "r1"]) == 1

            # ---- the control plane catches up on the queued alert
            # edges and restarts r1 at fire time (the second matching
            # edge is suppressed by the cooldown — exactly one bounce)
            assert restarted == []
            plane.tick(now=t0 + 0.5 * step[0])
            assert restarted == ["r1"], restarted
            pol = plane.policies()[0]
            assert pol.last_action["outcome"] == "restarted_r1"
            assert pol.last_action["rule"] in ("probe_mismatch",
                                               "probe_deadman")
            assert box["proc"].poll() is None       # respawn is alive
            assert p1.poll() is not None            # old process is gone
            # same weights, same deterministic capture → same oracle
            assert box["golden"]["version"] == golden1["version"]

            # ---- recovery: healthy beats until the mismatch ages out
            # of both windows and the deadman resets
            for _ in range(20):
                beat()
                if set(states[-1].values()) == {"OK"}:
                    break
            assert set(states[-1].values()) == {"OK"}, \
                [(r.name, r.state, r.last_detail)
                 for r in prober.engine.rules()]
            assert any(e["event"] == "probe_target_recovered"
                       and e.get("target") == "r1" for e in rec.events())
            assert {p.get("rule") for ev, p in edges
                    if ev == "alert_resolved"} >= {"probe_mismatch",
                                                   "probe_deadman"}
            assert restarted == ["r1"]          # cooldown held: no flap

            # ---- zero probe entries in ANY response cache: r0 was only
            # ever probed (empty LRU); r1's respawn only probed too
            status, stats0 = _get_json(port0, "/v1/models/drill")
            assert status == 200 and stats0["cache"]["entries"] == 0
            status, stats1b = _get_json(box["port"], "/v1/models/drill")
            assert status == 200 and stats1b["cache"]["entries"] == 0

            # ---- the incident reconstructs from GET /events alone
            status, evdoc = _get_json(ui_port, "/events")
            assert status == 200
            names = [e["event"] for e in evdoc["events"]]
            for needed in ("probe_target_failing", "health_problem",
                           "alert_firing", "control_action",
                           "probe_target_recovered", "alert_resolved"):
                assert needed in names, names
            assert names.index("probe_target_failing") \
                < names.index("control_action") \
                < names.index("probe_target_recovered")

            # ---- lifecycle: timed-join stop leaves no thread behind
            prober.stop()
            assert not prober.running()
            assert "prober" not in [t.name for t in threading.enumerate()]
        finally:
            prober.stop()
            prober.engine.clear()
            plane.clear()
            rec.clear()
            get_tracer().clear()
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=30)
            ui.stop()
