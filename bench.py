"""Benchmark harness.

Default run prints the BASELINE.json north-star metric (ResNet50
ComputationGraph training, images/sec on one chip) as JSON lines on stdout —
possibly SEVERAL: a stale-marked replay of the last banked number at startup,
then the fresh measurement (or a stale-marked/error final line) when the run
resolves. THE CONTRACT IS LAST-LINE-WINS: the most recent parseable headline
line is the run's result; earlier lines exist so that a kill at any moment
still leaves something parseable. ``--all`` also
benchmarks every config BASELINE.md commits to (LeNet MNIST, VGG16, GravesLSTM
char-RNN with TBPTT, Word2Vec skip-gram, Keras-imported inception-style model
under ParallelWrapper), writes the results into ``BASELINE.json.published``,
and still prints the single ResNet50 JSON line last.

Throughput accounting matches the reference's ``PerformanceListener``
(samples/sec; ``optimize/listeners/PerformanceListener.java:22-23``). Synthetic
inputs follow the reference's ``BenchmarkDataSetIterator`` pattern. The whole
train step (forward, AD backward, updater, param update) is a single jitted
XLA computation; params in f32, matmul/conv compute in bfloat16 on the MXU
(see PERF.md for the measurement史 and the roofline analysis).
"""
from __future__ import annotations

import datetime
import json
import os
import signal
import sys
import threading
import time

import numpy as np


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%dT%H:%M:%SZ")


def _apply_platform_override():
    """``BENCH_PLATFORM=cpu`` forces the JAX platform via config (the
    sitecustomize pins JAX_PLATFORMS at interpreter start, so the env var
    alone is too late) — used to smoke-test the harness off-TPU.

    Also enables a PERSISTENT XLA compilation cache (``BENCH_COMPILE_CACHE``,
    default ``.jax_cache/`` next to this file; ``0`` disables): every
    ``--one`` config runs in its own subprocess, so without it each sweep
    member re-pays its full compile — with it, repeated sweeps/retries hit
    the disk cache, shrinking the window a wedging tunnel can bite."""
    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        import jax
        jax.config.update("jax_platforms", plat)


def _enable_compile_cache():
    """Persistent XLA compilation cache (``BENCH_COMPILE_CACHE``, default
    ``.jax_cache/`` next to this file; ``0`` disables). Called ONLY from the
    ``--one`` child AFTER ``jax.devices()`` proved device contact — (a) the
    parent must never touch backend init (a wedged tunnel would hang it;
    that is what the subprocess probe exists for), and (b) TPU/axon only:
    XLA:CPU AOT entries are machine-flag sensitive (the loader warns about
    SIGILL on mismatch) and the CPU path is just the harness smoke test."""
    cache = os.environ.get("BENCH_COMPILE_CACHE", "")
    if cache == "0":
        return
    try:
        import jax
        if jax.default_backend() not in ("tpu", "axon"):
            return
        if not cache:
            cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 ".jax_cache")
        # ride the package's compile-once-fleet wiring (compilecache/):
        # same knobs as before, plus the jax.monitoring listener that
        # feeds jit_persistent_cache_hits_total — the --one record's
        # jitwatch block then splits disk hits from true compiles
        from deeplearning4j_tpu.compilecache import enable
        enable(cache)     # logs + degrades to live compiles on failure
    except Exception as e:  # cache is an optimization, never fatal
        print(f"# compile cache disabled: {e}", file=sys.stderr)


_PROBE_SRC = ("import os, jax\n"
              "p = os.environ.get('BENCH_PLATFORM')\n"
              "if p: jax.config.update('jax_platforms', p)\n"
              "jax.devices()\n")


def _hb():
    """Heartbeat for the parent's wedge watchdog: touch the file named by
    ``BENCH_HB`` (set by ``_run_one_subprocess``) at each progress point —
    value fetches (``_sync``), device contact at child start, and the slow
    host-side milestones (h5 generation, Keras import). The one phase that
    CANNOT beat is a single in-flight XLA compile RPC, which is why the
    stale threshold defaults well above any compile observed on the tunnel
    (longest: low minutes) — see ``_run_one_subprocess``."""
    path = os.environ.get("BENCH_HB")
    if path:
        try:
            with open(path, "w") as fh:
                fh.write(str(time.time()))
        except OSError:
            pass


def _sync(x):
    """Reliable completion barrier: materialize the VALUE of (a leaf of) ``x``
    on the host. Under the axon TPU tunnel ``jax.block_until_ready`` can
    return before the device program finishes (measured: a VGG16 train step
    "completing" in 0.4 ms), so timing must gate on an actual device→host
    value transfer — the loss scalar, whose value transitively requires every
    queued step's compute."""
    import jax
    leaf = jax.tree_util.tree_leaves(x)[-1]
    out = np.asarray(leaf)
    _hb()                     # value fetched ⇒ genuine progress
    return out


def _time_steps(step_fn, n_warmup=3, n_timed=10):
    """Run ``step_fn(i)`` (must return a device value whose VALUE depends on
    the step's compute — the loss) and return the timed-phase duration."""
    out = None
    for i in range(n_warmup):
        out = step_fn(i)
    _sync(out)
    t0 = time.perf_counter()
    for i in range(n_warmup, n_warmup + n_timed):
        out = step_fn(i)
    _sync(out)
    return time.perf_counter() - t0


def _warm_time(fn, *args, iters=5):
    """Compile+warm ``fn(*args)`` once, then return mean seconds per call
    over ``iters`` calls — the shared timing harness for the perf_* scripts
    (same value-fetch gating rationale as :func:`_time_steps`)."""
    _sync(fn(*args))
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def _cnn_throughput(model_cls, batch, img, classes=1000, iters=10,
                    compute_dtype="bfloat16", **model_kw):
    """images/sec for a zoo CNN (ComputationGraph or MultiLayerNetwork) on
    synthetic data."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    model = model_cls(num_classes=classes, **model_kw)
    conf = model.conf()
    conf.global_conf.compute_dtype = compute_dtype
    is_graph = isinstance(conf, ComputationGraphConfiguration)
    net = (ComputationGraph(conf) if is_graph
           else MultiLayerNetwork(conf)).init()
    rng = np.random.default_rng(0)
    c, h, w = img
    f = jnp.asarray(rng.normal(size=(batch, c, h, w)), jnp.float32)
    l = jnp.asarray(np.eye(classes, dtype=np.float32)[
        rng.integers(0, classes, batch)])
    step = net._ensure_step()
    state = {"p": net.params, "s": net.states, "u": net.updater_state}
    key = jax.random.PRNGKey(0)

    feats = (f,) if is_graph else f
    labels = (l,) if is_graph else l

    def one(i):
        it = jnp.asarray(i, jnp.int32)
        state["p"], state["s"], state["u"], loss = step(
            state["p"], state["s"], state["u"], it, key, feats, labels,
            None, None)
        return loss

    dt = _time_steps(one, n_timed=iters)
    return batch * iters / dt


def bench_resnet50(batch=256):
    # batch 256: v5e is HBM-bandwidth-bound on ResNet50; smaller batches
    # under-amortize fixed per-step work (PERF.md has the batch sweep).
    # 25 timed iters: single runs of 10 showed a ~5% run-to-run band
    from deeplearning4j_tpu.models import ResNet50
    return _cnn_throughput(ResNet50, batch, (3, 224, 224), iters=25)


def bench_vgg16(batch=256):
    # batch 256: 1403 img/s = 126 TFLOPS = 64% MFU by XLA's flop count
    # (22.98 TF / 69.9 GB per step) — compute-bound; 128 gives 1311
    from deeplearning4j_tpu.models import VGG16
    return _cnn_throughput(VGG16, batch, (3, 224, 224))


def bench_lenet(batch=1024, n_iter=10, fits=10):
    """LeNet MNIST (MultiLayerNetwork) images/sec through the public fit
    path, using the framework's own small-model configs: ``iterations(10)``
    (reference 0.9.x multi-iteration minibatch, compiled here as ONE scanned
    XLA program) + ``CacheMode.DEVICE`` (HBM-resident batch). Without them
    LeNet is dispatch-latency-bound (~13 ms/step over the tunnel vs 1.1 ms
    scanned)."""
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.datasets.dataset import DataSet

    conf = LeNet(num_classes=10).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    conf.global_conf.cache_mode = "device"
    conf.global_conf.iterations = n_iter
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(batch, 1, 28, 28)).astype(np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    net.fit(ds)
    _sync(net.score_)
    t0 = time.perf_counter()
    for _ in range(fits):
        net.fit(ds)
    _sync(net.score_)
    return batch * fits * n_iter / (time.perf_counter() - t0)


def bench_graves_lstm(batch=64, seq_len=200, tbptt=50, vocab=80, width=512):
    """GravesLSTM char-RNN with TBPTT (the reference CudnnLSTMHelper's
    showcase config): characters/sec processed."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, BackpropType
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu import Adam

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Adam(learning_rate=1e-3)).activation("tanh")
            .compute_dtype("bfloat16")
            .cache_mode("device")  # epoch reuse: one H2D, HBM-resident after
            .list()
            .layer(GravesLSTM(n_in=vocab, n_out=width))
            .layer(GravesLSTM(n_in=width, n_out=width))
            .layer(RnnOutputLayer(n_in=width, n_out=vocab,
                                  activation="softmax", loss="mcxent"))
            .build())
    conf.backprop_type = BackpropType.TruncatedBPTT
    conf.tbptt_fwd_length = tbptt
    conf.tbptt_back_length = tbptt
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(batch, seq_len))
    f = np.eye(vocab, dtype=np.float32)[ids]          # [b, T, vocab]
    l = np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    from deeplearning4j_tpu.datasets.dataset import DataSet

    ds = DataSet(f, l)
    net.fit(ds)  # warmup/compile all TBPTT segment shapes
    _sync(net.score_)
    n = 3
    t0 = time.perf_counter()
    for _ in range(n):
        net.fit(ds)
    _sync(net.score_)  # value fetch: transitively waits on every segment step
    dt = time.perf_counter() - t0
    return batch * seq_len * n / dt


#: latched by bench_input_pipeline; embedded in its --one record so the
#: BENCH trajectory carries the prefetch-off/on ETL comparison, not just
#: the headline number
INPUT_PIPELINE_STATS = {}


def bench_input_pipeline(batch=256, n_batches=32, delay_ms=25.0, workers=8):
    """Input-bound benchmark (datasets/prefetch.py): the base iterator
    sleeps ``delay_ms`` per batch — a slow decode/augment stand-in — so a
    synchronous fit pays the full ETL latency on the training thread every
    step. Runs the same fit with the input pipeline OFF
    (``DL4J_TPU_PREFETCH_WORKERS=0``) and ON (multi-worker prefetch +
    device-put-ahead), reading ``etl_ms`` from the monitor registry, and
    latches the comparison into ``INPUT_PIPELINE_STATS`` for the ``--one``
    record. Headline value: images/sec with the pipeline on."""
    from deeplearning4j_tpu import (NeuralNetConfiguration,
                                    MultiLayerNetwork, Sgd)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.datasets.dataset import DataSet, DataSetIterator
    from deeplearning4j_tpu.monitor import get_registry

    class SlowIter(DataSetIterator):
        """Slow synthetic source: the per-batch cost (the sleep — a
        decode/augment stand-in) sits in ``__next__`` itself, so only
        CONCURRENT pulls can hide it. The counter is lock-guarded and the
        sleep runs outside the lock: safe for N prefetch workers."""

        def __init__(self, ds, n, delay_s):
            self._ds, self._n, self._delay = ds, n, delay_s
            self._pos = 0
            self._lock = threading.Lock()

        def __next__(self):
            with self._lock:
                if self._pos >= self._n:
                    raise StopIteration
                self._pos += 1
            time.sleep(self._delay)
            return self._ds

        def reset(self):
            with self._lock:
                self._pos = 0

        def batch(self):
            return self._ds.num_examples()

        def concurrent_pull_supported(self):
            return True

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Sgd(learning_rate=0.05)).activation("tanh")
            .list()
            .layer(DenseLayer(n_in=784, n_out=256))
            .layer(OutputLayer(n_in=256, n_out=10, activation="softmax",
                               loss="mcxent"))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(batch, 784)).astype(np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    etl_hist = get_registry().histogram(
        "training_etl_ms", "host wait for the next minibatch")

    def phase(n_workers):
        prev = os.environ.get("DL4J_TPU_PREFETCH_WORKERS")
        os.environ["DL4J_TPU_PREFETCH_WORKERS"] = str(n_workers)
        try:
            _, total0, n0 = etl_hist.state()
            t0 = time.perf_counter()
            net.fit(SlowIter(ds, n_batches, delay_ms / 1e3))
            _sync(net.score_)
            wall = time.perf_counter() - t0
            _, total1, n1 = etl_hist.state()
            served = max(n1 - n0, 1)
            etl_mean = (total1 - total0) / served
            return etl_mean, batch * served / wall
        finally:
            if prev is None:
                os.environ.pop("DL4J_TPU_PREFETCH_WORKERS", None)
            else:
                os.environ["DL4J_TPU_PREFETCH_WORKERS"] = prev

    net.fit(ds)                   # compile outside both timed phases
    _sync(net.score_)
    etl_sync, ips_sync = phase(0)
    etl_pre, ips_pre = phase(workers)
    INPUT_PIPELINE_STATS.update({
        "delay_ms": delay_ms, "workers": workers, "batches": n_batches,
        "etl_ms_sync": round(etl_sync, 3),
        "etl_ms_prefetch": round(etl_pre, 3),
        "etl_reduction": round(etl_sync / max(etl_pre, 1e-9), 1),
        "overlap_ratio": round(1.0 - etl_pre / max(etl_sync, 1e-9), 4),
        "sync_images_per_sec": round(ips_sync, 1),
        "prefetch_images_per_sec": round(ips_pre, 1),
    })
    return ips_pre


#: latched by bench_serving_latency; embedded in its --one record so the
#: BENCH trajectory starts tracking tail latency (p50/p99 vs offered QPS)
#: alongside img/s
SERVING_STATS = {}


def bench_serving_latency(qps_points=(50.0, 250.0), duration_s=4.0,
                          n_in=64, hidden=128, classes=10,
                          buckets=(1, 2, 4, 8, 16, 32), linger_ms=3.0,
                          max_queue_examples=64, pool_workers=64,
                          variants=True, zipf_pool=24, zipf_s=1.3,
                          cold_start=True):
    """Serving-tier tail latency (serving/ — docs/SERVING.md): an
    OPEN-LOOP load generator drives ``POST /v1/models/<name>/predict``
    on an in-process :class:`InferenceServer` at fixed offered QPS —
    requests fire on schedule whether or not earlier ones returned, so
    queueing delay shows up as tail latency instead of silently throttling
    the generator (closed-loop coordination would hide saturation).
    Sweeps ``qps_points``; per point latches {offered_qps, achieved_qps,
    p50_ms, p99_ms, reject_rate, mean_batch_size} into ``SERVING_STATS``.

    ``variants=True`` (ISSUE 11) additionally re-drives the SAME offered-
    QPS points against the data-plane configurations {f32-nocache (the
    main sweep), bf16, bf16+cache under a ZIPFIAN request mix} and
    latches a ``variants`` sub-block — {p50_ms, p99_ms, achieved_qps,
    cache_hit_rate, mean_batch_size} per point per variant — so the
    BENCH trajectory carries the precision/cache before-after, not just
    the headline. Headline value: main-sweep achieved QPS at the highest
    offered point."""
    from concurrent.futures import ThreadPoolExecutor
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu import (NeuralNetConfiguration,
                                    MultiLayerNetwork, Sgd,
                                    InferenceServer, ModelRegistry)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.monitor import (AlertEngine, MetricsHistory,
                                            default_serving_rules,
                                            get_registry)

    def make_server(model_name, precision="f32", cache_size=None):
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Sgd(learning_rate=0.05)).activation("tanh").list()
                .layer(DenseLayer(n_in=n_in, n_out=hidden))
                .layer(OutputLayer(n_in=hidden, n_out=classes,
                                   activation="softmax", loss="mcxent"))
                .build())
        net = MultiLayerNetwork(conf).init()
        registry = ModelRegistry()
        # warmup=True pre-compiles every bucket signature (in the serving
        # precision) OUTSIDE the timed sweep: serving cold-start is the
        # compile-cache item's problem; this config measures steady-state
        # scheduling + forward latency
        registry.register(model_name, net, batch_buckets=buckets,
                          linger_ms=linger_ms,
                          max_queue_examples=max_queue_examples,
                          default_deadline_ms=5000.0,
                          input_shape=(n_in,), warmup=True,
                          precision=precision, cache_size=cache_size)
        _hb()
        srv = InferenceServer(registry)
        port = srv.start(port=0)
        return srv, f"http://127.0.0.1:{port}/v1/models/{model_name}/predict"

    rng = np.random.default_rng(0)
    # one fixed payload for the nocache sweeps (the pre-ISSUE-11 shape),
    # a pool of distinct payloads for the Zipfian cache variant — the
    # "millions of users" mix where a hot head dominates
    payloads = [json.dumps(
        {"inputs": rng.normal(size=(1, n_in)).astype(np.float32).tolist()}
    ).encode() for _ in range(zipf_pool)]

    def fire(url, data, out, lock):
        t0 = time.perf_counter()
        try:
            req = urllib.request.Request(
                url, data=data,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
            code = 200
        except urllib.error.HTTPError as e:
            e.close()
            code = e.code
        except OSError:
            code = -1
        with lock:
            out.append((code, (time.perf_counter() - t0) * 1e3))

    def drive(offered, url, model_name, pick_payload, engine=None,
              cache_counters=None):
        batch_hist = get_registry().histogram("serving_batch_examples",
                                              "", model=model_name)
        out, lock = [], threading.Lock()
        n = int(offered * duration_s)
        period = 1.0 / offered
        c0 = ([c.value for c in cache_counters]
              if cache_counters else None)
        with ThreadPoolExecutor(max_workers=pool_workers) as pool:
            _, b_total0, b_n0 = batch_hist.state()
            t_start = time.perf_counter()
            for i in range(n):
                target = t_start + i * period
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
                pool.submit(fire, url, pick_payload(i), out, lock)
        wall = time.perf_counter() - t_start
        _, b_total1, b_n1 = batch_hist.state()
        _hb()
        lat_ok = sorted(l for c, l in out if c == 200)
        rejects = sum(1 for c, _ in out if c == 429)
        flushes = max(b_n1 - b_n0, 1)

        def pct(q):
            return lat_ok[min(int(q * (len(lat_ok) - 1)),
                              len(lat_ok) - 1)] if lat_ok else None
        hit_rate = None
        if cache_counters:
            hits = cache_counters[0].value - c0[0]
            misses = cache_counters[1].value - c0[1]
            if hits + misses:
                hit_rate = round(hits / (hits + misses), 4)
        point = {
            "offered_qps": offered,
            "sent": n,
            "achieved_qps": round(len(lat_ok) / wall, 1),
            "p50_ms": round(pct(0.50), 2) if lat_ok else None,
            "p99_ms": round(pct(0.99), 2) if lat_ok else None,
            "reject_rate": round(rejects / max(n, 1), 4),
            "mean_batch_size": round((b_total1 - b_total0) / flushes, 2),
            "cache_hit_rate": hit_rate,
        }
        if engine is not None:
            engine.evaluate(strict=False)
            point["alerts_fired"] = engine.firing()
        return point

    # ---- main sweep: f32, no cache, fixed payload, SLO rules watching.
    # The default serving rule pack over a fast-sampling history ring;
    # each offered-QPS point latches which rules were FIRING when the
    # point ended — and the LOWEST point must end alert-free (a healthy
    # server at trivial load with alerts firing means the bench or the
    # rules are broken)
    srv, url = make_server("bench")
    history = MetricsHistory(capacity=256, interval_s=0.25)
    engine = AlertEngine(history=history)
    engine.add(*default_serving_rules(
        model="bench", windows=(2.0, 4.0), p99_target_ms=250.0,
        queue_cap=max_queue_examples, for_seconds=0.0))
    # for_seconds=0: the sweep points are seconds long — the production
    # hold-down would mask every breach, and alerts_fired at the high
    # points is part of the latched record
    rule_names = [r.name for r in engine.rules()]
    history.start()
    try:
        points = [drive(q, url, "bench", lambda i: payloads[0],
                        engine=engine) for q in qps_points]
    finally:
        srv.stop()
        history.stop()
        # rules legitimately FIRING at a high-QPS point must not leave
        # alerts_firing{rule=}=1 squatting in the process-global registry
        # for the rest of the run — clear() records the closing edges
        engine.clear()
    assert not points[0]["alerts_fired"], (
        f"SLO rules FIRING at the lowest offered-QPS point "
        f"({qps_points[0]} qps): {points[0]['alerts_fired']} — a healthy "
        f"server at trivial load must be alert-free")
    SERVING_STATS.update({
        "buckets": list(buckets), "linger_ms": linger_ms,
        "max_queue_examples": max_queue_examples,
        "duration_s": duration_s, "points": points,
        "alert_rules": rule_names,
    })

    if variants:
        # ---- data-plane variants at the SAME offered-QPS points.
        # f32-nocache re-uses the main sweep's points verbatim (same
        # harness, same payload) so the comparison costs one sweep, not
        # two; bf16 and bf16+cache each get a fresh net + server so
        # precision flips and cache state never leak across variants.
        recorded = [{"variant": "f32-nocache", "precision": "f32",
                     "cache_size": None, "zipfian": False,
                     "points": points, "cache_hit_rate": None}]
        zrng = np.random.default_rng(1)
        zipf_idx = [int((zrng.zipf(zipf_s) - 1) % zipf_pool)
                    for _ in range(int(max(qps_points) * duration_s) + 1)]
        for variant, cache_size, zipfian in (
                ("bf16", None, False),
                ("bf16-cache", zipf_pool, True)):
            model_name = f"bench_{variant.replace('-', '_')}"
            srv, url = make_server(model_name, precision="bf16",
                                   cache_size=cache_size)
            counters = None
            if cache_size:
                counters = (
                    get_registry().counter("serving_cache_hits_total",
                                           model=model_name),
                    get_registry().counter("serving_cache_misses_total",
                                           model=model_name))
            pick = ((lambda i: payloads[zipf_idx[i]]) if zipfian
                    else (lambda i: payloads[0]))
            # the registry is process-global and the model name fixed:
            # the overall rate must diff against THIS sweep's start like
            # the per-point rate does, or a re-run in the same process
            # reports a blended stale figure
            base = [c.value for c in counters] if counters else None
            try:
                vpoints = [drive(q, url, model_name, pick,
                                 cache_counters=counters)
                           for q in qps_points]
            finally:
                srv.stop()
            overall = None
            if counters:
                hits, misses = (c.value - b0
                                for c, b0 in zip(counters, base))
                if hits + misses:
                    overall = round(hits / (hits + misses), 4)
            recorded.append({"variant": variant, "precision": "bf16",
                             "cache_size": cache_size, "zipfian": zipfian,
                             "points": vpoints,
                             "cache_hit_rate": overall})
        SERVING_STATS["variants"] = recorded

    if cold_start:
        # ---- compile-once fleet (ISSUE 12): cold-vs-warm cache-dir
        # serving warmup in child processes, latched as the --one
        # record's cold_start block (same net/buckets as the sweep)
        _measure_cold_start(n_in=n_in, hidden=hidden, classes=classes,
                            buckets=buckets)
    return points[-1]["achieved_qps"] or 0.0


#: latched by _measure_cold_start (driven from bench_serving_latency);
#: embedded in the --one record as its ``cold_start`` block so the BENCH
#: trajectory carries the compile-once-fleet before/after (ISSUE 12)
COLD_START_STATS = {}

#: child source for the cold-start measurement: ONE serving warmup —
#: build the same MLP the serving bench uses, register with warmup=True
#: (pre-compiles every bucket signature), report jitwatch's compile
#: seconds + the persistent hit/miss split. The PARENT points
#: DL4J_TPU_COMPILE_CACHE_DIR at a shared dir and runs this twice: the
#: first child populates the disk cache (cold), the second hits it
#: (warm) — the delta is exactly what a serving replica's cold start (or
#: a post-scale_to worker rejoin) saves fleet-wide.
_COLD_START_SRC = """
import json, os, sys
import jax
p = os.environ.get('BENCH_PLATFORM')
if p: jax.config.update('jax_platforms', p)
from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                Sgd, ModelRegistry)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
n_in, hidden, classes = (int(a) for a in sys.argv[1:4])
buckets = tuple(int(b) for b in sys.argv[4].split(','))
conf = (NeuralNetConfiguration.builder().seed(7)
        .updater(Sgd(learning_rate=0.05)).activation('tanh').list()
        .layer(DenseLayer(n_in=n_in, n_out=hidden))
        .layer(OutputLayer(n_in=hidden, n_out=classes,
                           activation='softmax', loss='mcxent'))
        .build())
net = MultiLayerNetwork(conf).init()
reg = ModelRegistry()
reg.register('coldstart', net, batch_buckets=buckets,
             input_shape=(n_in,), warmup=True)
from deeplearning4j_tpu.monitor.jitwatch import get_jit_registry
from deeplearning4j_tpu.compilecache import persistent_cache_counts
row = get_jit_registry().table().get('mln/output', {})
reg.close_all(drain=False)
print(json.dumps({'compile_s': row.get('compile_seconds', 0.0),
                  'compiles': row.get('compiles', 0),
                  'persistent_cache_hits':
                      row.get('persistent_cache_hits', 0),
                  'process': persistent_cache_counts()}))
"""


def _measure_cold_start(n_in=64, hidden=128, classes=10,
                        buckets=(1, 2, 4, 8, 16, 32), timeout_s=600):
    """Cold-start mode (ISSUE 12): run the serving warmup in a child
    process twice against one shared ``DL4J_TPU_COMPILE_CACHE_DIR`` —
    cold dir, then warm dir — and latch
    ``{cold_compile_s, warm_compile_s, speedup, ...}`` into
    ``COLD_START_STATS`` for the ``--one`` record's ``cold_start``
    block. Returns the stats dict, or None when a child failed (the
    record then simply carries no cold_start block — the headline must
    never fail over its garnish)."""
    import shutil
    import subprocess
    import tempfile

    d = tempfile.mkdtemp(prefix="bench_compile_cache_")
    argv = [str(n_in), str(hidden), str(classes),
            ",".join(str(b) for b in buckets)]
    runs = []
    try:
        for phase in ("cold", "warm"):
            env = dict(os.environ, DL4J_TPU_COMPILE_CACHE_DIR=d)
            try:
                p = subprocess.run(
                    [sys.executable, "-c", _COLD_START_SRC] + argv,
                    capture_output=True, env=env, timeout=timeout_s)
            except (subprocess.TimeoutExpired, OSError) as e:
                # a hung/unspawnable child must cost only the cold_start
                # garnish, never the already-measured sweep record
                print(f"# cold-start {phase} child did not complete: "
                      f"{e!r}", file=sys.stderr)
                return None
            if p.returncode != 0:
                print(f"# cold-start {phase} child failed "
                      f"rc={p.returncode}: "
                      f"{p.stderr.decode(errors='replace')[-300:]}",
                      file=sys.stderr)
                return None
            doc = None
            for line in reversed(p.stdout.decode().splitlines()):
                try:
                    doc = json.loads(line)
                    break
                except ValueError:
                    continue
            if doc is None:
                print(f"# cold-start {phase} child printed no record",
                      file=sys.stderr)
                return None
            runs.append(doc)
            _hb()
    finally:
        shutil.rmtree(d, ignore_errors=True)
    cold, warm = runs
    COLD_START_STATS.update({
        "buckets": list(buckets),
        "compiles": warm["compiles"],
        "cold_compile_s": round(cold["compile_s"], 4),
        "warm_compile_s": round(warm["compile_s"], 4),
        "speedup": round(cold["compile_s"]
                         / max(warm["compile_s"], 1e-9), 2),
        "cold_persistent_hits": cold["persistent_cache_hits"],
        "warm_persistent_hits": warm["persistent_cache_hits"],
    })
    return COLD_START_STATS


#: latched by bench_paramserver; embedded in its --one record so the BENCH
#: trajectory carries the 1-server-full-vector vs N-server-delta wire and
#: throughput comparison, not just the headline number
PARAMSERVER_STATS = {}


def bench_paramserver(steps=32, n_in=1024, hidden=1024, classes=10,
                      batch=64, num_servers=3):
    """Parameter-server fleet throughput (paramserver/sharded.py): the same
    async-SGD fit run against (a) ONE server with dense full-vector pulls
    (the PR-1 wire: staleness=0 re-pulls the whole parameter vector every
    step) and (b) a ``num_servers``-node sharded group speaking the proto
    v3 delta wire (per-shard sparse pushes in parallel, journal-replay
    pulls). Latches {steps/sec, push+pull wire bytes per step} for both
    into ``PARAMSERVER_STATS`` for the ``--one`` record; wire bytes come
    from the master's own exact per-instance client counters
    (``push_bytes``/``pull_bytes``), deltaed around the timed fit.
    Headline value: N-server-delta steps/sec."""
    from deeplearning4j_tpu import (NeuralNetConfiguration,
                                    MultiLayerNetwork, DataSet,
                                    ListDataSetIterator, Sgd)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import DistributedMultiLayerNetwork
    from deeplearning4j_tpu.paramserver import (
        ParameterServer, ParameterServerTrainingMaster,
        ShardedParameterServerGroup)

    rng = np.random.default_rng(0)
    batches = [DataSet(rng.normal(size=(batch, n_in)).astype(np.float32),
                       np.eye(classes, dtype=np.float32)[
                           rng.integers(0, classes, batch)])
               for _ in range(steps)]

    def build_net():
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Sgd(learning_rate=0.05)).activation("tanh").list()
                .layer(DenseLayer(n_in=n_in, n_out=hidden))
                .layer(OutputLayer(n_in=hidden, n_out=classes,
                                   activation="softmax", loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    def run(servers, delta):
        net = build_net()
        group = srv = None
        if servers == 1 and not delta:
            srv = ParameterServer(port=0)
            address = srv.address
        else:
            group = ShardedParameterServerGroup(servers)
            address = group.address
        try:
            master = (ParameterServerTrainingMaster.Builder(address)
                      .staleness(0).threshold(1e-3).backoff(0.01)
                      .delta_push(delta).build())
            dnet = DistributedMultiLayerNetwork(net, master)
            dnet.fit(ListDataSetIterator(batches[:2]))   # compile, un-timed
            c0 = dict(master.client.metrics.snapshot()["counters"])
            t0 = time.perf_counter()
            dnet.fit(ListDataSetIterator(batches))
            dt = time.perf_counter() - t0
            c1 = master.client.metrics.snapshot()["counters"]
            wire = (c1["push_bytes"] - c0["push_bytes"]
                    + c1["pull_bytes"] - c0["pull_bytes"])
            master.client.close()
            return steps / dt, wire / steps
        finally:
            if srv is not None:
                srv.stop()
            if group is not None:
                group.stop()

    sps_dense, wire_dense = run(1, delta=False)
    sps_delta, wire_delta = run(num_servers, delta=True)
    n_params = n_in * hidden + hidden + hidden * classes + classes
    PARAMSERVER_STATS.update({
        "num_servers": num_servers, "steps": steps, "params": n_params,
        "dense_steps_per_sec": round(sps_dense, 1),
        "delta_steps_per_sec": round(sps_delta, 1),
        "dense_wire_bytes_per_step": int(wire_dense),
        "delta_wire_bytes_per_step": int(wire_delta),
        "wire_reduction": round(wire_dense / max(wire_delta, 1.0), 1),
        "speedup": round(sps_delta / max(sps_dense, 1e-9), 2),
    })
    return sps_delta


#: latched by bench_paramserver_overlap; embedded in its --one record so
#: the BENCH trajectory carries the sync-vs-overlap comparison AND the
#: per-phase breakdown that proves WHERE the win came from (comms hidden
#: under compute), not just the headline number
PARAMSERVER_OVERLAP_STATS = {}


def bench_paramserver_overlap(steps=16, n_in=256, hidden=256, classes=10,
                              batch=2048, min_delay_s=0.005):
    """Latency-hiding hot loop (paramserver/overlap.py): the same async-SGD
    fit run twice against ONE server — sync (``overlap=False``, today's
    fully-serial loop) and overlapped (``overlap=True``: a comms worker
    encodes+pushes step k while the device computes step k+1) — with an
    INJECTED per-push transport delay (``push_delay_s`` ≥ 5 ms: a real
    cross-host RTT, where localhost would measure ~100 µs and hide
    nothing worth hiding). The delay is calibrated to the measured
    compute+d2h mean of an un-delayed sync run, putting the comms round
    and the device step in the same regime — exactly where the pipeline
    earns its keep: sync pays compute + comms per step, overlap pays
    ~max(compute, comms). Latches {steps/sec both modes, speedup, exact
    per-phase means from ``train_step_phase_ms`` registry deltas, wall
    step means} into ``PARAMSERVER_OVERLAP_STATS`` for the ``--one``
    record. Headline value: overlap steps/sec.

    Shape note: SMALL model × LARGE batch on purpose. On the CPU harness
    the 'device' shares cores with the comms worker, so a big parameter
    vector makes the worker's encode fight the next step's compute and
    eat the win; ~68K params keeps encode sub-ms so the comms round is
    dominated by the injected sleep (which contends with nothing), while
    batch=2048 keeps compute comparable to the delay."""
    from deeplearning4j_tpu import (NeuralNetConfiguration,
                                    MultiLayerNetwork, DataSet,
                                    ListDataSetIterator, Sgd)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import DistributedMultiLayerNetwork
    from deeplearning4j_tpu.monitor import get_registry
    from deeplearning4j_tpu.paramserver import (
        ParameterServer, ParameterServerClient,
        ParameterServerTrainingMaster)

    rng = np.random.default_rng(0)
    batches = [DataSet(rng.normal(size=(batch, n_in)).astype(np.float32),
                       np.eye(classes, dtype=np.float32)[
                           rng.integers(0, classes, batch)])
               for _ in range(steps)]

    def build_net():
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Sgd(learning_rate=0.05)).activation("tanh").list()
                .layer(DenseLayer(n_in=n_in, n_out=hidden))
                .layer(OutputLayer(n_in=hidden, n_out=classes,
                                   activation="softmax", loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    def phase_totals():
        # (ms-sum, n) per phase straight from the registry children —
        # exact per-mode means come from deltas around each timed fit
        # (the registry is process-global and cumulative across runs)
        reg = get_registry()
        out = {}
        for p in ("compute", "d2h", "encode", "push"):
            _, total, n = reg.histogram(
                "train_step_phase_ms",
                "paramserver training hot-loop phase latency",
                phase=p).state()
            out[p] = (total, n)
        _, total, n = reg.histogram(
            "train_step_wall_ms",
            "paramserver training wall time per step").state()
        out["wall"] = (total, n)
        return out

    def run(overlap, delay_s):
        net = build_net()
        srv = ParameterServer(port=0)
        try:
            # the injected-latency client rides the master's ctor seam;
            # count_own_pushes=False keeps staleness=0 from re-pulling the
            # full vector after every own push (single worker, contiguous
            # versions) so the comms round under test is push-only
            client = ParameterServerClient(
                srv.address, staleness=0, max_retries=5, backoff=0.01,
                push_delay_s=delay_s)
            master = ParameterServerTrainingMaster(
                srv.address, staleness=0, threshold=1e-3, backoff=0.01,
                count_own_pushes=False, client=client, overlap=overlap)
            dnet = DistributedMultiLayerNetwork(net, master)
            dnet.fit(ListDataSetIterator(batches[:2]))   # compile, un-timed
            p0 = phase_totals()
            t0 = time.perf_counter()
            dnet.fit(ListDataSetIterator(batches))
            dt = time.perf_counter() - t0
            p1 = phase_totals()
            master.close()
            phase_ms = {k: round((p1[k][0] - p0[k][0])
                                 / max(p1[k][1] - p0[k][1], 1), 3)
                        for k in p1}
            return steps / dt, phase_ms
        finally:
            srv.stop()

    # calibrate: delay ≈ the step's device-side cost, floored at 5 ms
    _, cal = run(overlap=False, delay_s=0.0)
    delay_s = max(float(min_delay_s), (cal["compute"] + cal["d2h"]) / 1e3)

    sps_sync, ph_sync = run(overlap=False, delay_s=delay_s)
    sps_over, ph_over = run(overlap=True, delay_s=delay_s)
    wall_sync = ph_sync.pop("wall")
    wall_over = ph_over.pop("wall")
    PARAMSERVER_OVERLAP_STATS.update({
        "steps": steps, "params": n_in * hidden + hidden
                                  + hidden * classes + classes,
        "push_delay_ms": round(delay_s * 1e3, 3),
        "steps_per_sec_sync": round(sps_sync, 2),
        "steps_per_sec_overlap": round(sps_over, 2),
        "speedup": round(sps_over / max(sps_sync, 1e-9), 2),
        "phase_ms": {"sync": ph_sync, "overlap": ph_over},
        "wall_ms_sync": round(wall_sync, 3),
        "wall_ms_overlap": round(wall_over, 3),
        # wall < Σ phases is the proof the comms ran UNDER the compute
        "hidden_ms_per_step": round(
            sum(ph_over.values()) - wall_over, 3),
    })
    return sps_over


CONTROL_LOOP_STATS = {}


def bench_control_loop(slow_ms=120.0, shards=2, timeout_s=60.0):
    """Closed-loop control chaos drill (control/plane.py, docs/CONTROL.md):
    an inference server with a faultable model + a sharded paramserver
    fleet run under the control plane's daemon (serving-pressure +
    shard-restart policies), then BOTH faults land at once — the model
    turns slow (p99 SLO breach) and a shard server is killed
    (``shard_server_down``) — and the drill measures the wall time until
    the system is back to an alert-free steady state with ZERO human
    intervention: admission stepped then restored, the shard restarted
    from its latched snapshot. Latches {time_to_recover_s, actions_taken,
    alerts_fired} (plus per-incident reaction times) into
    ``CONTROL_LOOP_STATS`` for the ``--one`` record. Headline value:
    seconds to recover (lower is better, unlike the throughput benches —
    trajectory tooling reads the unit)."""
    import json as _json
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.control import (get_control_plane,
                                            serving_pressure_policy,
                                            shard_restart_policy)
    from deeplearning4j_tpu.monitor import (BurnRateRule, get_alert_engine,
                                            get_flight_recorder,
                                            get_history)
    from deeplearning4j_tpu.paramserver import (
        ShardedParameterServerClient, ShardedParameterServerGroup)
    from deeplearning4j_tpu.serving import InferenceServer

    class FaultableModel:
        def __init__(self):
            self.delay_s = 0.0

        def output(self, x, mask=None):
            if self.delay_s:
                time.sleep(self.delay_s)
            x = np.asarray(x)
            return np.full((x.shape[0], 2), 1.0, np.float32)

    model = FaultableModel()
    srv = InferenceServer()
    srv.register("drill", model, batch_buckets=(1, 2, 4), linger_ms=0.5,
                 max_queue_examples=64, qps_window_s=1.0)
    port = srv.start(port=0)
    url = f"http://127.0.0.1:{port}/v1/models/drill/predict"
    engine, hist = get_alert_engine(), get_history()
    rec = get_flight_recorder()
    engine.add(BurnRateRule("drill_p99", kind="latency", target_ms=40.0,
                            windows=(1.5, 3.0),
                            latency_labels={"model": "drill"},
                            for_seconds=0.2))
    n = 64
    group = ShardedParameterServerGroup(shards)
    client = ShardedParameterServerClient(group.addresses, max_retries=0,
                                          backoff=0.01, down_backoff=0.05)
    plane = get_control_plane()
    plane.add(serving_pressure_policy(srv.registry, "drill",
                                      rules=("drill_p99",),
                                      cooldown_s=0.5),
              shard_restart_policy(group, cooldown_s=0.5))
    served = srv.registry.get("drill")
    body = _json.dumps({"inputs": [[1.0, 2.0]]}).encode("utf-8")

    def post():
        req = urllib.request.Request(
            url, data=body, headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=30) as r:
                r.read()
        except urllib.error.HTTPError as e:
            e.read()
            e.close()

    def drive(k):
        for _ in range(k):
            post()
        hist.sample()
        engine.evaluate(strict=False)

    def actions(name):
        return [a for a in plane.actions() if a["action"] == name]

    events0 = len(rec.events())
    try:
        client.set_params(np.zeros(n, np.float32))
        plane.start(interval_s=0.05)
        drive(6)                                  # healthy baseline

        # ---- both faults land; the recovery clock starts HERE
        t_fault = time.perf_counter()
        model.delay_s = slow_ms / 1e3
        group.kill(1)                             # latches the snapshot
        client.push_encoded((np.array([0, 1], np.int32),
                             np.array([1, 1], np.int8), 0.5, n))

        stepped = restarted = None
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            drive(3)
            if stepped is None and actions("set_admission"):
                stepped = time.perf_counter() - t_fault
                # the clamp shed the load: the incident's cause clears,
                # and from here recovery is the loop's job alone
                model.delay_s = 0.0
            if restarted is None and actions("restart"):
                restarted = time.perf_counter() - t_fault
            if stepped is not None and restarted is not None \
                    and not engine.firing() \
                    and actions("restore_admission"):
                break
            time.sleep(0.05)
        t_recover = time.perf_counter() - t_fault
        recovered = (not engine.firing()
                     and bool(actions("restore_admission"))
                     and getattr(group.servers[1], "_running", False))
        fresh = rec.events()[events0:]
        CONTROL_LOOP_STATS.update({
            "time_to_recover_s": round(t_recover, 3),
            "recovered": recovered,
            "time_to_admission_step_s":
                round(stepped, 3) if stepped is not None else None,
            "time_to_shard_restart_s":
                round(restarted, 3) if restarted is not None else None,
            "actions_taken": len([e for e in fresh
                                  if e["event"] == "control_action"]),
            "alerts_fired": len([e for e in fresh
                                 if e["event"] == "alert_firing"]),
            "admission_restored":
                served.batcher.max_queue_examples == 64,
        })
        return t_recover
    finally:
        plane.stop()
        plane.clear()
        engine.remove("drill_p99")
        client.close()
        group.stop()
        srv.stop()


FLEET_SCRAPE_STATS = {}


def bench_fleet_scrape(replicas=3, ticks=25, warm_requests=4):
    """Scrape-plane collector bench (monitor/collector.py): K in-process
    inference replicas polled over real HTTP by one TelemetryCollector
    into a PRIVATE FleetState, measuring the per-target ``/telemetry``
    scrape cost and the whole-tick overhead around the scrapes (fleet
    merge + history sample + alert evaluation). Latches
    {scrape_ms_p50, scrape_ms_p99, targets, merged_series,
    tick_overhead_ms, scrape_errors} into ``FLEET_SCRAPE_STATS`` for
    the ``--one`` record. Headline value: scrape p99 ms (lower is
    better — trajectory tooling reads the unit)."""
    import json as _json
    import urllib.request

    from deeplearning4j_tpu.monitor.collector import TelemetryCollector
    from deeplearning4j_tpu.monitor.fleet import FleetState
    from deeplearning4j_tpu.serving import InferenceServer

    class TinyModel:
        def output(self, x, mask=None):
            x = np.asarray(x)
            return np.full((x.shape[0], 2), 1.0, np.float32)

    servers = []
    collector = TelemetryCollector(fleet=FleetState())
    body = _json.dumps({"inputs": [[1.0, 2.0]]}).encode("utf-8")
    try:
        for i in range(int(replicas)):
            srv = InferenceServer()
            srv.register(f"m{i}", TinyModel(), batch_buckets=(1, 2, 4),
                         linger_ms=0.0, max_queue_examples=64)
            port = srv.start(port=0)
            servers.append(srv)
            collector.add_target(f"replica{i}", f"127.0.0.1:{port}")
            # a few real requests so each reply carries latency series
            # (and exemplars) — an idle registry would undercount the
            # merge cost
            for _ in range(int(warm_requests)):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/models/m{i}/predict",
                    data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=10) as r:
                    r.read()
        collector.tick()                        # cursor-priming pass
        samples, overhead, errors = [], [], 0
        for _ in range(int(ticks)):
            summary = collector.tick()
            errors += len(summary["errors"])
            ms = list(summary["scrape_ms"].values())
            samples.extend(ms)
            overhead.append(summary["duration_ms"] - sum(ms))
        samples.sort()

        def pct(q):
            return samples[min(len(samples) - 1,
                               int(q * (len(samples) - 1)))]

        p99 = round(pct(0.99), 3)
        FLEET_SCRAPE_STATS.update({
            "scrape_ms_p50": round(pct(0.50), 3),
            "scrape_ms_p99": p99,
            "targets": int(replicas),
            "merged_series": len(collector.fleet_dump()),
            "tick_overhead_ms": round(sum(overhead) / len(overhead), 3),
            "scrape_errors": errors,
        })
        return p99
    finally:
        collector.stop()
        for srv in servers:
            srv.stop()


PROBE_OVERHEAD_STATS = {}


_PROBE_REPLICA_SRC = r"""
import json, sys
import numpy as np
from deeplearning4j_tpu.serving import InferenceServer

class TinyModel:
    def output(self, x, mask=None):
        x = np.asarray(x)
        return np.full((x.shape[0], 2), 1.0, np.float32)

srv = InferenceServer()
served = srv.register("probed", TinyModel(), input_shape=(2,),
                      batch_buckets=(1, 2, 4), linger_ms=0.0,
                      max_queue_examples=64, cache_size=16)
golden = served.golden()
port = srv.start(port=0)
print(json.dumps({"port": port, "golden": golden}), flush=True)
sys.stdin.read()
"""

#: prober child for bench_probe_overhead: a Prober in its OWN process
#: (the deployment shape — co-located with neither the replica nor the
#: latency-measuring driver), started/stopped between phases over a
#: stdin line protocol: "start <interval_s>" / "stop" / "quit" (each
#: ack'd with "ok"); "quit" prints the target's final snapshot row
_PROBE_PROBER_SRC = r"""
import json, sys
from deeplearning4j_tpu.monitor.probes import Prober

cfg = json.loads(sys.stdin.readline())
p = Prober()
p.add_target("bench", cfg["url"], cfg["golden"])
for line in sys.stdin:
    cmd = line.split()
    if cmd[0] == "start":
        p.start(interval_s=float(cmd[1]))
    elif cmd[0] == "stop":
        p.stop()
    elif cmd[0] == "quit":
        p.stop()
        print(json.dumps(p.snapshot()["targets"]["bench"]), flush=True)
        break
    print("ok", flush=True)
"""


def bench_probe_overhead(requests=2000, probe_qps=(1.0, 4.0)):
    """Probe-plane interference bench (monitor/probes.py): serving
    p50/p99 over real HTTP against a REPLICA SUBPROCESS with the prober
    OFF, then at each probe QPS point with a live Prober firing
    golden-set probes at the same replica — the deployment shape (the
    probe plane is external by definition; co-locating the prober inside
    the replica would measure GIL contention no real probe causes). The
    probe plane's pitch is "black-box monitoring at negligible serving
    cost" — this latches the receipt: {p50_off_ms, p99_off_ms, points:
    [{probe_qps, p50_ms, p99_ms, p99_overhead_pct, probes,
    last_outcome}], max_p99_overhead_pct, cache_entries_after} into
    ``PROBE_OVERHEAD_STATS`` for the ``--one`` record. Headline value:
    worst p99 overhead percent across the QPS points (lower is better;
    the acceptance pin is < 5%). The replica serves with its response
    cache ON: real traffic lands exactly one entry and every probe
    bypasses it, so ``cache_entries_after == 1`` restates the drill's
    cache-purity invariant under load."""
    import json as _json
    import subprocess
    import urllib.request

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"        # numpy model: never wait on a device
    root = os.path.dirname(os.path.abspath(__file__))
    env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_REPLICA_SRC],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=root)
    doc = _json.loads(proc.stdout.readline())
    port, golden = int(doc["port"]), doc["golden"]
    url = f"http://127.0.0.1:{port}/v1/models/probed/predict"
    body = _json.dumps({"inputs": [[1.0, 2.0]]}).encode("utf-8")
    pproc = subprocess.Popen(
        [sys.executable, "-c", _PROBE_PROBER_SRC],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=root)
    pproc.stdin.write(_json.dumps(
        {"url": f"127.0.0.1:{port}", "golden": golden}) + "\n")
    pproc.stdin.flush()

    def prober_cmd(cmd):
        pproc.stdin.write(cmd + "\n")
        pproc.stdin.flush()
        return pproc.stdout.readline().strip()

    def drive(n):
        lat = []
        for _ in range(int(n)):
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            with urllib.request.urlopen(req, timeout=10) as r:
                r.read()
            lat.append((time.perf_counter() - t0) * 1e3)
        return lat

    def pct(lat, q):
        lat = sorted(lat)
        return round(lat[min(len(lat) - 1, int(q * (len(lat) - 1)))], 3)

    try:
        # warm the whole serving path until the startup transient is
        # gone: the first ~100 requests of a fresh replica show one-off
        # multi-ms hiccups (thread-pool growth, allocator warmup) that
        # would land entirely in whichever pool is measured first
        drive(max(150, int(requests) // 4))
        # interleaved + shuffled design: loopback p99s are
        # sub-millisecond, so two phases measured at different times
        # mostly measure machine drift, not probes. Each rep drives one
        # OFF segment and one ON segment per QPS point in a (seeded)
        # shuffled order — slow machine periods and position effects
        # land evenly across the pools — and the per-phase pools are
        # compared as wholes, so the p99 index sits on a real 1% tail
        # instead of a tiny segment's max sample
        import random
        rng = random.Random(0)
        reps = 5
        per = max(1, int(requests) // reps)
        off = []
        on = {float(qps): [] for qps in probe_qps}
        for _ in range(reps):
            phases = [None] + [float(q) for q in probe_qps]
            rng.shuffle(phases)
            for qps in phases:
                # every phase opens with an UNMEASURED ~32-request burst:
                # the serving path shows a one-off ~5ms hiccup ~25
                # requests into a fresh burst (observed with the prober
                # completely absent), and a phase comparison is only fair
                # if that transient lands in nobody's measured pool
                if qps is None:
                    drive(32)
                    off += drive(per)
                    continue
                # each start fires an immediate probe, so every rep
                # guarantees at least one probe lands inside its phase
                assert prober_cmd(f"start {1.0 / qps}") == "ok"
                try:
                    drive(32)
                    on[qps] += drive(per)
                finally:
                    assert prober_cmd("stop") == "ok"
        p50_off = pct(off, 0.50)
        p99_off = pct(off, 0.99)
        PROBE_OVERHEAD_STATS.update({
            "p50_off_ms": p50_off, "p99_off_ms": p99_off,
            "requests_per_point": per * reps, "points": []})
        snap = _json.loads(prober_cmd("quit"))
        worst = 0.0
        for qps in probe_qps:
            overhead = ((pct(on[float(qps)], 0.99) - p99_off)
                        / max(p99_off, 1e-9) * 100.0)
            worst = max(worst, overhead)
            PROBE_OVERHEAD_STATS["points"].append({
                "probe_qps": float(qps),
                "p50_ms": pct(on[float(qps)], 0.50),
                "p99_ms": pct(on[float(qps)], 0.99),
                "p99_overhead_pct": round(overhead, 2),
                "probes": snap["probes"],
                "last_outcome": snap["last_outcome"],
            })
        worst = round(max(0.0, worst), 2)
        PROBE_OVERHEAD_STATS["max_p99_overhead_pct"] = worst
        # cache purity under load: drive()'s identical bodies land ONE
        # entry; every probe bypassed the cache or this would be 2
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/models/probed",
                timeout=10) as r:
            PROBE_OVERHEAD_STATS["cache_entries_after"] = \
                _json.loads(r.read())["cache"]["entries"]
        return worst
    finally:
        for p in (pproc, proc):
            p.kill()
            p.wait(timeout=30)


INCIDENT_OVERHEAD_STATS = {}


def bench_incident_overhead(requests=400, slow_ms=80.0, timeout_s=30.0):
    """Incident-plane interference bench (monitor/incidents.py): the
    chaos-drill shape — serving goes slow, the p99 burn rule fires, a
    control policy steps admission, the model heals, the alert resolves
    — run TWICE: once bare, once with a live :class:`IncidentRecorder`
    capturing at the fire edge and persisting the bundle at resolve.
    Serving p99 is measured over identical healthy request pools on
    both sides of the drill; the incident plane's pitch is "the black
    box is free for the serving path" (capture runs on the recorder's
    own tick thread, persistence outside every lock) and this latches
    the receipt: {p99_off_ms, p99_on_ms, overhead_pct, capture_ms_p99,
    bundle_bytes, incidents, fired, resolved} into
    ``INCIDENT_OVERHEAD_STATS`` for the ``--one`` record. Headline
    value: p99 overhead percent with the recorder on (lower is better;
    the acceptance pin is <= 1% on the drill p99). The on-phase must
    end with exactly ONE persisted ``.dl4jinc`` bundle — the drill's
    merged edges are one incident, not a bundle per edge."""
    import json as _json
    import tempfile
    import urllib.error
    import urllib.request

    from deeplearning4j_tpu.control import (get_control_plane,
                                            serving_pressure_policy)
    from deeplearning4j_tpu.monitor import (BurnRateRule, IncidentRecorder,
                                            get_alert_engine, get_history,
                                            get_registry)
    from deeplearning4j_tpu.serving import InferenceServer

    class FaultableModel:
        def __init__(self):
            self.delay_s = 0.0

        def output(self, x, mask=None):
            if self.delay_s:
                time.sleep(self.delay_s)
            x = np.asarray(x)
            return np.full((x.shape[0], 2), 1.0, np.float32)

    def pct(lat, q):
        lat = sorted(lat)
        return round(lat[min(len(lat) - 1, int(q * (len(lat) - 1)))], 3)

    def phase(dump_dir):
        """One full drill; ``dump_dir`` not None → recorder ON. Returns
        (healthy latencies, phase stats)."""
        model = FaultableModel()
        srv = InferenceServer()
        srv.register("incdrill", model, batch_buckets=(1, 2, 4),
                     linger_ms=0.5, max_queue_examples=64,
                     qps_window_s=1.0)
        port = srv.start(port=0)
        url = f"http://127.0.0.1:{port}/v1/models/incdrill/predict"
        body = _json.dumps({"inputs": [[1.0, 2.0]]}).encode("utf-8")
        engine, hist = get_alert_engine(), get_history()
        hist.clear()                    # stale slow-phase samples from a
        engine.add(BurnRateRule(       # prior phase must not pre-burn
            "incdrill_p99", kind="latency", target_ms=40.0,
            windows=(1.5, 3.0), latency_labels={"model": "incdrill"},
            for_seconds=0.2))
        plane = get_control_plane()
        plane.add(serving_pressure_policy(srv.registry, "incdrill",
                                          rules=("incdrill_p99",),
                                          factor=0.5, min_cap=8,
                                          cooldown_s=0.5))
        rec = None
        if dump_dir is not None:
            rec = IncidentRecorder(engine=engine, dump_dir=dump_dir)
            rec.start(interval_s=0.05)

        def post(timed=None):
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json"})
            t0 = time.perf_counter()
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    r.read()
            except urllib.error.HTTPError as e:
                e.read()
                e.close()
            if timed is not None:
                timed.append((time.perf_counter() - t0) * 1e3)

        lat = []
        stats = {"fired": False, "resolved": False}
        try:
            plane.start(interval_s=0.05)
            for _ in range(64):             # unmeasured warmup
                post()
            for _ in range(int(requests) // 2):   # healthy pool A
                post(timed=lat)
            hist.sample()
            engine.evaluate(strict=False)
            # ---- the fault lands; drive (untimed) until the rule fires
            model.delay_s = slow_ms / 1e3
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                for _ in range(3):
                    post()
                hist.sample()
                engine.evaluate(strict=False)
                if engine.firing():
                    stats["fired"] = True
                    break
            # ---- heal; drive until the alert resolves (and, with the
            # recorder on, the resolve has persisted the bundle)
            model.delay_s = 0.0
            while time.monotonic() < deadline:
                for _ in range(3):
                    post()
                hist.sample()
                engine.evaluate(strict=False)
                if engine.firing():
                    continue
                if rec is not None and not any(
                        inc.path for inc in rec.incidents()):
                    continue
                stats["resolved"] = True
                break
            for _ in range(int(requests) // 2):   # healthy pool B
                post(timed=lat)
            if rec is not None:
                rows = rec.snapshot()["incidents"]
                stats["incidents"] = len(rows)
                stats["bundle_bytes"] = sum(
                    r["bundle_bytes"] or 0 for r in rows)
            return lat, stats
        finally:
            if rec is not None:
                rec.stop()
            plane.stop()
            plane.clear()
            engine.remove("incdrill_p99")
            srv.stop()

    dump_dir = tempfile.mkdtemp(prefix="incbench_")
    lat_off, _ = phase(None)
    lat_on, on_stats = phase(dump_dir)
    p99_off, p99_on = pct(lat_off, 0.99), pct(lat_on, 0.99)
    overhead = round(max(
        0.0, (p99_on - p99_off) / max(p99_off, 1e-9) * 100.0), 2)
    cap = get_registry().histogram("incident_capture_ms").summary()
    INCIDENT_OVERHEAD_STATS.update({
        "p99_off_ms": p99_off, "p99_on_ms": p99_on,
        "p50_off_ms": pct(lat_off, 0.50), "p50_on_ms": pct(lat_on, 0.50),
        "overhead_pct": overhead,
        "requests_per_phase": (int(requests) // 2) * 2,
        "capture_ms_p99": round(cap.get("p99_ms", 0.0), 3),
        "bundle_bytes": on_stats.get("bundle_bytes", 0),
        "incidents": on_stats.get("incidents", 0),
        "fired": on_stats["fired"], "resolved": on_stats["resolved"],
        "dump_dir": dump_dir,
    })
    return overhead


PARALLEL_MEMORY_STATS = {}

#: child source for the too-few-devices fallback: re-run the grid on a
#: virtual 8-device CPU mesh in a fresh interpreter (set_cpu_devices must
#: beat backend init — impossible in the already-initialized parent).
#: Same pattern as _COLD_START_SRC. argv: steps n_in hidden classes batch
#: model_extent bench_path
_PM_CHILD_SRC = """
import importlib.util, json, sys
sys.path.insert(0, __import__('os').path.dirname(sys.argv[7]))
from deeplearning4j_tpu.compat import set_cpu_devices
# size the virtual mesh from the requested model extent, or the child
# would re-fail the parent's device check and recurse another child
set_cpu_devices(max(8, 2 * int(sys.argv[6])))
spec = importlib.util.spec_from_file_location('bench_pm_child', sys.argv[7])
mod = importlib.util.module_from_spec(spec)
spec.loader.exec_module(mod)
mod.bench_parallel_memory(*[int(a) for a in sys.argv[1:7]])
print(json.dumps(mod.PARALLEL_MEMORY_STATS))
"""


def bench_parallel_memory(steps=8, n_in=256, hidden=1024, classes=16,
                          batch=64, model_extent=2):
    """Unified-mesh memory/throughput grid (parallel/mesh.py substrate):
    the same Adam fit under {replicated, ws (ZeRO-1 optimizer-state
    sharding), fsdp (ZeRO-3 sharded storage)} × {1-D data mesh, 2-D
    data × model mesh with megatron TP rules}. Latches per cell
    {steps_per_sec, state_bytes_per_device (EXACT: params+updater bytes
    resident on device 0 — the quantity ZeRO divides), bytes_in_use /
    peak_bytes (backend memory stats; None on statless backends like the
    CPU harness — peak is process-cumulative, read it only for the cell
    that interests you in a dedicated run)} into
    ``PARALLEL_MEMORY_STATS`` for the ``--one`` record's
    ``parallel_memory`` block. Headline value: fsdp-on-2-D steps/sec —
    the composed topology the substrate exists for."""
    from deeplearning4j_tpu import (NeuralNetConfiguration,
                                    MultiLayerNetwork, DataSet,
                                    ListDataSetIterator, Adam)
    from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.parallel import ParallelWrapper
    from deeplearning4j_tpu.monitor.jitwatch import sample_device_memory
    import jax

    if len(jax.devices()) < 2 * model_extent:
        # single-chip harness (TPU v5 lite0 / plain CPU): the grid needs a
        # real multi-device mesh, so run it on a virtual 8-device CPU mesh
        # in a child interpreter (set_cpu_devices must beat backend init)
        # and latch the child's stats, marked as such
        import subprocess
        argv = [str(int(v)) for v in (steps, n_in, hidden, classes, batch,
                                      model_extent)]
        p = subprocess.run(
            [sys.executable, "-c", _PM_CHILD_SRC] + argv
            + [os.path.abspath(__file__)],
            capture_output=True, text=True, timeout=1200,
            env={k: v for k, v in os.environ.items()
                 if k != "JAX_PLATFORMS"} | {"JAX_PLATFORMS": "cpu"})
        _hb()
        if p.returncode != 0:
            raise RuntimeError(
                f"parallel_memory CPU-mesh child failed rc={p.returncode}: "
                f"{p.stderr.strip()[-500:]}")
        stats = json.loads(p.stdout.strip().splitlines()[-1])
        stats["virtual_cpu_mesh"] = True
        PARALLEL_MEMORY_STATS.update(stats)
        return stats["grid"]["fsdp_2d"]["steps_per_sec"]

    rng = np.random.default_rng(0)
    batches = [DataSet(rng.normal(size=(batch, n_in)).astype(np.float32),
                       np.eye(classes, dtype=np.float32)[
                           rng.integers(0, classes, batch)])
               for _ in range(steps)]

    def build_net():
        conf = (NeuralNetConfiguration.builder().seed(7)
                .updater(Adam(learning_rate=1e-3)).activation("tanh").list()
                .layer(DenseLayer(n_in=n_in, n_out=hidden))
                .layer(DenseLayer(n_in=hidden, n_out=hidden))
                .layer(OutputLayer(n_in=hidden, n_out=classes,
                                   activation="softmax", loss="mcxent"))
                .build())
        return MultiLayerNetwork(conf).init()

    def state_bytes_dev0(net):
        """Exact params+updater bytes resident on device 0 (a replicated
        leaf costs its full size per device; a sharded leaf 1/N)."""
        total = 0
        for leaf in (jax.tree_util.tree_leaves(net.params)
                     + jax.tree_util.tree_leaves(net.updater_state)):
            shards = getattr(leaf, "addressable_shards", None)
            total += (shards[0].data.nbytes if shards
                      else getattr(leaf, "nbytes", 0))
        return total

    def mem_gauges():
        mem = sample_device_memory().get("devices") or {}
        in_use = [r.get("bytes_in_use") for r in mem.values()
                  if r.get("bytes_in_use") is not None]
        peak = [r.get("peak_bytes_in_use") for r in mem.values()
                if r.get("peak_bytes_in_use") is not None]
        return (max(in_use) if in_use else None,
                max(peak) if peak else None)

    def run(style, two_d):
        net = build_net()
        b = ParallelWrapper.Builder(net)
        if two_d:
            b = b.tensor_parallel(model_extent)
        if style == "ws":
            b = b.weight_update_sharding()
        elif style == "fsdp":
            b = b.fsdp()
        pw = b.build()
        it = ListDataSetIterator(batches)
        pw.fit(it, epochs=1)                 # compile + placement, un-timed
        it0 = pw.iteration_count
        t0 = time.perf_counter()
        pw.fit(it, epochs=2)
        _sync(net.score_)
        dt = time.perf_counter() - t0
        n_steps = pw.iteration_count - it0
        in_use, peak = mem_gauges()
        _hb()
        return {"steps_per_sec": round(n_steps / dt, 2),
                "state_bytes_per_device": int(state_bytes_dev0(net)),
                "bytes_in_use": in_use, "peak_bytes": peak}

    grid = {}
    for style in ("replicated", "ws", "fsdp"):
        for two_d in (False, True):
            key = f"{style}_{'2d' if two_d else '1d'}"
            grid[key] = run(style, two_d)
    n_params = (n_in * hidden + hidden + hidden * hidden + hidden
                + hidden * classes + classes)
    PARALLEL_MEMORY_STATS.update({
        "steps": steps, "params": n_params, "model_extent": model_extent,
        "devices": len(jax.devices()), "grid": grid,
        "virtual_cpu_mesh": False,
        # the memory win as one number: ZeRO-3 state bytes vs replicated,
        # on the composed 2-D mesh
        "fsdp_vs_replicated_state_ratio": round(
            grid["fsdp_2d"]["state_bytes_per_device"]
            / max(grid["replicated_2d"]["state_bytes_per_device"], 1), 4),
    })
    return grid["fsdp_2d"]["steps_per_sec"]


def bench_word2vec(n_sentences=20000, sent_len=40, vocab_target=5000):
    """Word2Vec skip-gram (HS) words/sec through the jitted kernels.
    800k-word corpus so steady-state batch throughput dominates the one-time
    vocab build + kernel compile (PerformanceListener-style accounting)."""
    from deeplearning4j_tpu.nlp import Word2Vec

    rng = np.random.default_rng(0)
    zipf = rng.zipf(1.3, size=n_sentences * sent_len) % vocab_target
    words = zipf.reshape(n_sentences, sent_len)
    sentences = [" ".join(f"w{t}" for t in row) for row in words]
    w2v = Word2Vec(vector_length=128, window=5, epochs=1, batch_size=8192,
                   min_word_frequency=1)
    t0 = time.perf_counter()
    w2v.fit(sentences)
    dt = time.perf_counter() - t0
    return n_sentences * sent_len / dt


def _inception_v3_h5():
    """The REAL tf.keras InceptionV3 (313 layers, 23.9M params at 1000
    classes), weights=None (random init — zero egress), saved once to a
    local cache in legacy h5 format. The round-3 bench fed a 36 KB 16×16
    toy while BASELINE.md promised 'Keras-imported InceptionV3' — this
    makes the metric measure the promised model (VERDICT r3 item 7)."""
    cache = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         ".bench_cache")
    path = os.path.join(cache, "inception_v3_299.h5")
    if os.path.exists(path):
        return path
    os.makedirs(cache, exist_ok=True)
    os.environ.setdefault("CUDA_VISIBLE_DEVICES", "-1")
    import tensorflow as tf
    tf.keras.utils.set_random_seed(7)
    m = tf.keras.applications.InceptionV3(weights=None,
                                          input_shape=(299, 299, 3),
                                          classes=1000)
    m.save(path)
    _hb()       # minutes of host-side work — not a wedge
    return path


def bench_keras_import_parallel(batch_per_step=128, iters=10):
    """Real Keras-imported InceptionV3 (299×299, 1000 classes) trained
    under ParallelWrapper (BASELINE.md config 6; single chip → one worker,
    the multi-chip path is exercised by the virtual-mesh dryrun)."""
    import jax
    from deeplearning4j_tpu.keras.model_import import KerasModelImport
    from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMode
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

    net = KerasModelImport.import_keras_model_and_weights(_inception_v3_h5())
    _hb()       # 313-layer import parsed — host-side progress
    net.gc.compute_dtype = "bfloat16"
    # epoch reuse of the 147 MB global batch: without the device cache the
    # measurement is host-link-bound (26 img/s over the axon tunnel), not a
    # property of the training step
    net.gc.cache_mode = "device"
    rng = np.random.default_rng(0)
    n_dev = len(jax.devices())
    dsets = [DataSet(rng.normal(size=(batch_per_step // n_dev, 3, 299, 299)
                                ).astype(np.float32),
                     np.eye(1000, dtype=np.float32)[
                         rng.integers(0, 1000, batch_per_step // n_dev)])
             for _ in range(n_dev)]
    pw = (ParallelWrapper.Builder(net).training_mode(TrainingMode.AVERAGING)
          .averaging_frequency(1)
          # images + bf16 compute: host-side cast halves the H2D bytes of
          # the warm-up/first-epoch transfer, bit-identical results
          # (parity-tested). The TIMED loop reuses the device cache
          # (cache_mode='device'), so this shortens the un-timed first
          # pass — the first-epoch path the overlap work targets — without
          # touching the steady-state number
          .host_transfer_dtype("bfloat16").build())
    pw.fit(ListDataSetIterator(dsets))  # compile + one pass
    _sync(net.params)
    t0 = time.perf_counter()
    for _ in range(iters):
        pw.fit(ListDataSetIterator(dsets))
    # value-fetch a param leaf (pw.last_score is already a host float);
    # axon block_until_ready is unreliable — see _sync
    _sync(net.params)
    dt = time.perf_counter() - t0
    return batch_per_step * iters / dt


def bench_transformer_lm(batch=4, seq_len=8192, vocab=4096, embed=512,
                         heads=8, blocks=8, iters=10):
    """Net-new flagship: decoder-only TransformerLM (pre-LN residual CG;
    T=8192 rides the Pallas flash-attention kernel — the dense path would
    materialize 8 × [b, h, T, T] logits) tokens/sec. Not a BASELINE.md
    config (the reference predates transformers) — measured as the
    framework's own long-context headline."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import TransformerLM

    m = TransformerLM(vocab_size=vocab, embed_dim=embed, num_heads=heads,
                      num_blocks=blocks, seed=1)
    conf = m.conf()
    conf.global_conf.compute_dtype = "bfloat16"
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, vocab, size=(batch, seq_len)),
                      jnp.float32)
    l = jax.nn.one_hot(jnp.asarray(
        rng.integers(0, vocab, size=(batch, seq_len))), vocab,
        dtype=jnp.float32)
    step = net._ensure_step()
    state = {"p": net.params, "s": net.states, "u": net.updater_state}
    key = jax.random.PRNGKey(0)

    def one(i):
        it = jnp.asarray(i, jnp.int32)
        state["p"], state["s"], state["u"], loss = step(
            state["p"], state["s"], state["u"], it, key, (ids,), (l,),
            None, None)
        return loss

    dt = _time_steps(one, n_timed=iters)
    return batch * seq_len * iters / dt


LINT_FULL_STATS = {}


def bench_lint_full(repeats=3):
    """tpulint whole-package cost (analysis/): wall-seconds for one full
    default run — every rule, including the interprocedural lock graph
    (THR003/THR004) and the racegraph lockset pass (THR005) — against
    the shipped baseline. Pure host CPU, no backend needed. Latches
    {wall_s, files, rules, findings_new, findings_baselined} into
    ``LINT_FULL_STATS`` for the ``--one`` record so a linter cost
    regression shows up in the trajectory next to the numbers it taxes
    (the pre-commit hook and the tier-1 self-host guard both pay this
    wall time). Headline value: best-of-N wall seconds (lower is
    better)."""
    from deeplearning4j_tpu.analysis import (Linter, load_baseline,
                                             DEFAULT_BASELINE_PATH,
                                             PACKAGE_ROOT, all_rules)
    baseline = load_baseline(DEFAULT_BASELINE_PATH)
    best, res = None, None
    for _ in range(int(repeats)):
        t0 = time.perf_counter()
        res = Linter().run([PACKAGE_ROOT], baseline=baseline)
        dt = time.perf_counter() - t0
        best = dt if best is None or dt < best else best
    LINT_FULL_STATS.update({
        "wall_s": round(best, 3),
        "files": res.files_checked,
        "rules": len(all_rules()),
        "findings_new": len(res.new),
        "findings_baselined": len(res.baselined),
    })
    return round(best, 3)


# Sweep order = information value under a flapping tunnel (round-4 lesson:
# a 50-min up-window banked only the configs that happened to come first).
# Smallest honest measurement (lenet) proves the window, then the configs
# whose numbers are NEW (lstm under the unroll/bf16 levers, inception
# under the device cache + overlap, transformer = never measured), then
# the configs with stable prior numbers (resnet/vgg/w2v) — the resnet
# headline has its own dedicated stage anyway.
ALL_BENCHES = [
    ("lenet_mnist_images_per_sec", "images/sec", bench_lenet),
    ("input_pipeline_images_per_sec", "images/sec", bench_input_pipeline),
    ("paramserver_steps_per_sec", "steps/sec", bench_paramserver),
    ("paramserver_overlap_steps_per_sec", "steps/sec",
     bench_paramserver_overlap),
    ("parallel_memory", "steps/sec", bench_parallel_memory),
    ("serving_latency_qps", "req/sec", bench_serving_latency),
    ("control_loop_time_to_recover_s", "s", bench_control_loop),
    ("fleet_scrape_p99_ms", "ms", bench_fleet_scrape),
    ("probe_overhead_p99_pct", "%", bench_probe_overhead),
    ("incident_overhead_pct", "%", bench_incident_overhead),
    ("lint_full_wall_s", "s", bench_lint_full),
    ("graves_lstm_charrnn_chars_per_sec", "chars/sec", bench_graves_lstm),
    ("keras_inception_parallelwrapper_images_per_sec", "images/sec",
     bench_keras_import_parallel),
    ("transformer_lm_tokens_per_sec", "tokens/sec", bench_transformer_lm),
    ("resnet50_imagenet_images_per_sec", "images/sec", bench_resnet50),
    ("vgg16_imagenet_images_per_sec", "images/sec", bench_vgg16),
    ("word2vec_skipgram_words_per_sec", "words/sec", bench_word2vec),
]


def _await_backend(max_wait_s=None, probe_timeout=120) -> bool:
    """Guard against a wedged axon tunnel: PJRT client creation can hang
    FOREVER when the relay holds a stale lease (observed in rounds 3/4).
    Probe ``jax.devices()`` in a subprocess under a timeout, with a
    backoff-growing retry schedule for up to 15 minutes by default — the
    relay lease has been observed to reset on its own, and spending part of
    the bench window waiting beats zeroing the round. Default capped WELL
    below the driver's ~30-min kill window (round-4 lesson: a 30-min probe
    window lost the race and the driver got nothing; the startup replay +
    deadline guard now backstop this, but the probe budget must still leave
    time for a real measurement). Override upward only deliberately via
    BENCH_PROBE_WINDOW_S. Returns False rather than hanging."""
    import subprocess

    if max_wait_s is None:
        max_wait_s = float(os.environ.get("BENCH_PROBE_WINDOW_S", 900))
    t_start = time.monotonic()
    wait, attempt = 60.0, 0
    while True:
        attempt += 1
        proc = subprocess.Popen(
            [sys.executable, "-c", _PROBE_SRC],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE)
        _CHILDREN.add(proc)    # the guards kill in-flight probes too
        try:
            try:
                _, perr = proc.communicate(timeout=probe_timeout)
                if proc.returncode == 0:
                    return True
                msg = perr.decode(errors="replace").strip()[-200:]
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.communicate()
                msg = f"probe timed out after {probe_timeout}s"
        finally:
            _CHILDREN.discard(proc)
        elapsed = time.monotonic() - t_start
        remaining = max_wait_s - elapsed
        if remaining <= 0:
            print(f"# TPU backend unreachable after {attempt} probes over "
                  f"{elapsed:.0f}s: {msg}", file=sys.stderr)
            return False
        print(f"# TPU backend unreachable (probe {attempt}, {elapsed:.0f}s "
              f"elapsed): {msg}; retrying in {min(wait, remaining):.0f}s",
              file=sys.stderr)
        time.sleep(min(wait, remaining))
        # cap low: the round-4 tunnel FLAPPED (one transient recovery in
        # hours of wedge) — frequent probes maximize the chance of catching
        # an up-window, and each costs nothing while the backend is down
        wait = min(wait * 2, 120.0)


def _run_one_subprocess(name, timeout_s=2400):
    """Run one bench config in its own subprocess so a tunnel wedge mid-run
    loses only that config, not the whole sweep (round-3 VERDICT: 'emit
    partial results per-config so one hang doesn't zero the sweep').
    The generous timeout only fires when genuinely wedged — normal compiles
    are well under it (killing a healthy compile can wedge the tunnel).
    A HEARTBEAT watchdog cuts wedge detection from ``timeout_s`` to
    ``BENCH_HB_STALE_S`` (default 1200 s): the child touches ``BENCH_HB``
    at every value fetch (``_sync``), at device contact on startup, and at
    the slow host-side milestones, so a stale file means no progress for
    that long — kill early and let the caller re-probe (the round-4 tunnel
    FLAPPED; a fast kill catches more up-windows). Tradeoff, accepted
    deliberately: a single compile RPC cannot beat, so a compile longer
    than the threshold would be killed as wedged (observed compiles are
    minutes at worst; raise BENCH_HB_STALE_S if a model ever legitimately
    needs more — killing a healthy compile can wedge the tunnel, which is
    why the threshold is generous and the caller re-probes after every
    kill)."""
    import subprocess
    import tempfile

    stale_s = float(os.environ.get("BENCH_HB_STALE_S", 1200))
    hb = tempfile.NamedTemporaryFile(prefix=f"bench_hb_{name}_",
                                     delete=False)
    hb.close()
    env = dict(os.environ, BENCH_HB=hb.name)
    proc = None
    try:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--one", name],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env)
        _CHILDREN.add(proc)    # the guards kill a live bench child too
        t0 = time.monotonic()
        start_wall = time.time()
        timed_out = stale = False
        while True:
            try:
                out, err = proc.communicate(timeout=15)
                break
            except subprocess.TimeoutExpired:
                last_beat = max(os.path.getmtime(hb.name), start_wall)
                if time.monotonic() - t0 > timeout_s:
                    timed_out = True
                elif time.time() - last_beat > stale_s:
                    stale = True
                else:
                    continue
                proc.kill()
                out, err = proc.communicate()
                break
        p = subprocess.CompletedProcess(proc.args, proc.returncode, out, err)
    finally:
        if proc is not None:
            _CHILDREN.discard(proc)
        try:
            os.unlink(hb.name)
        except OSError:
            pass
    if timed_out or stale:
        why = (f"TIMED OUT after {timeout_s}s" if timed_out
               else f"heartbeat stale > {stale_s:.0f}s")
        print(f"# {name} {why} (tunnel wedged mid-run?)", file=sys.stderr)
        return None
    sys.stderr.write(p.stderr.decode(errors="replace"))
    if p.returncode != 0:
        print(f"# {name} FAILED rc={p.returncode}", file=sys.stderr)
        return None
    for line in reversed(p.stdout.decode().splitlines()):
        try:
            doc = json.loads(line)
            if doc.get("one") == name:
                if doc.get("monitor") is not None:
                    # latch the child's registry snapshot so the final
                    # headline (the line BENCH_*.json banks) carries the
                    # runtime metrics of the run that produced the number
                    _FINAL["monitor"] = doc["monitor"]
                if doc.get("jitwatch") is not None:
                    # same for the compile-cost block: the headline must
                    # separate compile seconds from steady-state step time
                    _FINAL["jitwatch"] = doc["jitwatch"]
                return doc.get("value")
        except (ValueError, AttributeError):
            continue
    print(f"# {name}: no result line in subprocess output", file=sys.stderr)
    return None


def _read_baseline():
    """Prior published baseline, read BEFORE any update — vs_baseline
    compares against the previous round's number, not this run's."""
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                               "BASELINE.json")) as fh:
            base_doc = json.load(fh)
        return base_doc, base_doc.get("published", {}).get(
            "resnet50_imagenet_images_per_sec")
    except Exception:  # tpulint: disable=EXC001 — no baseline file = no headline, by design
        return None, None


def _write_partial(base_doc, results):
    """Persist whatever has succeeded SO FAR — a later hang must not lose
    earlier configs' numbers. ``published`` always holds the LAST measured
    value; ``last_measured`` stamps when each metric was actually captured
    on hardware, so "published" can never silently become best-ever
    cherry-picking across rounds (VERDICT r4 weak 5)."""
    if base_doc is None:
        return
    base_doc.setdefault("published", {}).update(results)
    stamps = base_doc.setdefault("last_measured", {})
    now = _utcnow()
    for name in results:
        stamps[name] = now
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE.json")
    # atomic replace: a SIGTERM/deadline os._exit mid-write must never
    # truncate the file the startup replay depends on
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(base_doc, fh, indent=2)
    os.replace(tmp, path)


# ---------------------------------------------------------------------------
# The always-parseable-headline contract (VERDICT r4, "do this" item 1).
#
# The driver runs ``python bench.py`` under a kill timeout and parses a JSON
# line from stdout. Round 4 handed it ``parsed: null``: the 30-min probe
# window met the driver window and the process died mid-probe having printed
# nothing. Three defenses, layered:
#   1. STARTUP REPLAY — before touching any backend, print the last-banked
#      headline from BASELINE.json with ``"stale": true`` and its
#      ``last_measured`` stamp. From second ~0 there is always a parseable
#      line on stdout, whatever happens later.
#   2. SIGTERM FLUSH — ``timeout`` sends SIGTERM before SIGKILL; the handler
#      prints a final headline (fresh if one was measured, else stale-marked)
#      and exits. Also covers Ctrl-C (SIGINT).
#   3. HARD DEADLINE — a watchdog thread flushes the final headline and
#      exits at ``BENCH_DEADLINE_S`` (default 1440 s, comfortably under the
#      observed ~1800 s driver kill), so even a SIGKILL-only driver sees a
#      completed process. Default mode only — ``--all`` sweeps are run by
#      the burst harness under its own horizon (set BENCH_DEADLINE_S to
#      override there too).
# The reference's PerformanceListener never makes reporting conditional on
# a healthy run (optimize/listeners/PerformanceListener.java:22-23); same
# rule here.
# ---------------------------------------------------------------------------

# NO lock: _emit_final must be callable from a signal handler, where a
# non-reentrant lock held by the interrupted main thread would deadlock.
# The one-shot guard is a plain flag; rc is latched so a late signal after
# a stale-only emit exits with the SAME code, not a fabricated 0. The only
# races this leaves are microsecond windows that at worst duplicate or drop
# the FINAL line — the startup replay line is already on stdout by then, so
# the last-line-wins contract still yields a parseable headline.
_FINAL = {
    "emitted": False,          # one-shot guard for the FINAL line
    "rc": 2,                   # latched exit code of the final emit
    "fresh_value": None,       # measured this run, on hardware
    "stale_value": None,       # replayed from BASELINE.json
    "stale_utc": None,
    "base_val": None,
}

# live child processes (bench --one subprocesses, backend probes): the
# signal/deadline handlers kill these before os._exit so a dying parent
# never orphans a TPU-holding child against the tunnel
_CHILDREN = set()


def _backend_stale() -> bool:
    """Whether a measurement taken NOW would be off-harness: True unless
    the process is talking to a real TPU backend (tpu/axon). The ``--one``
    record carries this as its ``stale`` field so the trajectory tooling
    can filter CPU-fallback / smoke-test numbers automatically — the
    r03–r05 tunnel-outage replays were only flagged in prose, and prose
    does not filter. (The parent's BASELINE.json replay headlines carry
    their own ``stale: true`` via :func:`_headline_doc`.)"""
    try:
        import jax
        return jax.default_backend() not in ("tpu", "axon")
    except Exception:  # tpulint: disable=EXC001 — unreachable backend = nothing fresh to trust
        return True


def _monitor_snapshot():
    """The measuring process's monitor-registry snapshot (step/ETL
    histograms, transport bytes, …), embedded in each emitted record so
    BENCH_*.json correlates the perf trajectory with the runtime metrics
    behind it. None when the registry is unavailable or empty — a bench
    record must never fail over its telemetry garnish."""
    try:
        from deeplearning4j_tpu.monitor import get_registry
        return get_registry().snapshot() or None
    except Exception as e:
        print(f"# monitor snapshot unavailable: {e}", file=sys.stderr)
        return None


def _jitwatch_snapshot():
    """Compact jitwatch block (compiles / compile seconds / cache-miss
    ratio, per-fn detail) embedded in each --one record and the final
    headline, so BENCH trajectories separate compile cost from
    steady-state step time. None when nothing was monitored — the
    record must never fail over its telemetry garnish."""
    try:
        from deeplearning4j_tpu.monitor.jitwatch import get_jit_registry
        table = get_jit_registry().table()
        if not table:
            return None
        compiles = sum(r["compiles"] for r in table.values())
        calls = sum(r["calls"] for r in table.values())
        return {
            "compiles": compiles,
            "compile_s": round(sum(r["compile_seconds"]
                                    for r in table.values()), 3),
            "cache_miss_ratio": (round(compiles / calls, 4)
                                 if calls else None),
            "per_fn": {n: {"compiles": r["compiles"],
                           "calls": r["calls"],
                           "compile_s": r["compile_seconds"]}
                       for n, r in table.items()},
        }
    except Exception as e:
        print(f"# jitwatch snapshot unavailable: {e}", file=sys.stderr)
        return None


def _headline_doc(value, base_val, *, stale=False, measured_utc=None,
                  error=None):
    vs = (value / base_val) if (base_val and value) else (1.0 if value else None)
    doc = {"metric": "resnet50_imagenet_images_per_sec", "value": value,
           "unit": "images/sec",
           "vs_baseline": round(vs, 3) if vs else None}
    if stale:
        doc["stale"] = True
    if measured_utc:
        doc["measured_utc"] = measured_utc
    if error:
        doc["error"] = error
    # the measurement child's monitor + jitwatch snapshots, lifted by
    # _run_one_subprocess — absent on stale replays and error paths
    if _FINAL.get("monitor") is not None:
        doc["monitor"] = _FINAL["monitor"]
    if _FINAL.get("jitwatch") is not None:
        doc["jitwatch"] = _FINAL["jitwatch"]
    return doc


def _print_line(doc):
    # os.write to fd 1: async-signal-safe (no buffered-writer reentrancy
    # when called from the SIGTERM handler) and atomic for short lines
    os.write(1, (json.dumps(doc) + "\n").encode())


def _emit_startup_replay():
    """Defense 1: a parseable line on stdout before any backend contact."""
    base_doc, base_val = _read_baseline()
    _FINAL["base_val"] = base_val
    if base_doc is not None and base_val:
        utc = base_doc.get("last_measured", {}).get(
            "resnet50_imagenet_images_per_sec")
        _FINAL["stale_value"] = base_val
        _FINAL["stale_utc"] = utc
        _print_line(_headline_doc(
            base_val, base_val, stale=True, measured_utc=utc,
            error="replayed last banked measurement; fresh run in progress"))
    return base_doc, base_val


def _emit_final(error=None):
    """Print the final headline exactly once: fresh if this run measured
    one, else the stale replay (marked), else an explicit error object.
    Returns the exit code the caller should use. Signal-handler safe: no
    locks, no buffered I/O (see the _FINAL comment for the race analysis)."""
    if _FINAL["emitted"]:
        return _FINAL["rc"]
    if _FINAL["fresh_value"] is not None:
        doc = _headline_doc(_FINAL["fresh_value"], _FINAL["base_val"],
                            measured_utc=_utcnow())
        rc = 0
    elif _FINAL["stale_value"] is not None:
        doc = _headline_doc(
            _FINAL["stale_value"], _FINAL["base_val"], stale=True,
            measured_utc=_FINAL["stale_utc"],
            error=error or "no fresh measurement; replaying last banked")
        rc = 2
    else:
        doc = _headline_doc(None, None, error=error or "no measurement")
        rc = 2
    _FINAL["rc"] = rc
    _FINAL["emitted"] = True
    _print_line(doc)
    return rc


def _kill_children():
    """Best-effort kill of live probe/bench subprocesses so the dying
    parent never leaves an orphan holding the TPU tunnel."""
    for p in list(_CHILDREN):
        try:
            p.kill()
        except Exception:  # tpulint: disable=EXC001 — best-effort kill on the way down
            pass


def _install_guards(deadline_s):
    """Defenses 2+3: SIGTERM/SIGINT flush and the hard-deadline watchdog."""
    def _on_signal(signum, frame):
        rc = _emit_final(error=f"killed by signal {signum} before a fresh "
                               f"measurement completed")
        _kill_children()
        os._exit(rc)

    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _on_signal)
        except (ValueError, OSError):
            pass
    if deadline_s and deadline_s > 0:
        # published so default mode can size the bench child's timeout to
        # the REMAINING budget — a child allowed to outlive the deadline
        # would always be killed mid-measurement by the watchdog instead
        _FINAL["deadline_at"] = time.monotonic() + deadline_s

        def _on_deadline():
            rc = _emit_final(error=f"self-imposed deadline {deadline_s:.0f}s "
                                   f"reached (driver window protection)")
            _kill_children()
            os._exit(rc)
        t = threading.Timer(deadline_s, _on_deadline)
        t.daemon = True
        t.start()


def _budget_timeout(default_s: float) -> float:
    """Child timeout capped to the remaining self-deadline budget (minus a
    flush margin) so the measurement subprocess, not the watchdog, is what
    gives up first — preserving the parent's re-probe/retry path."""
    at = _FINAL.get("deadline_at")
    if at is None:
        return default_s
    return max(120.0, min(default_s, at - time.monotonic() - 60.0))


def main():
    # parent mode NEVER imports jax in-process (device contact and platform
    # override belong to the probe/child subprocesses) — so the startup
    # replay line hits stdout within numpy-import time, not jax-import time
    if "--one" in sys.argv:
        _apply_platform_override()
        # child mode: run exactly one config in-process, print a result line.
        # --write additionally persists it into BASELINE.json.published
        # (the burst harness re-measures individual configs this way)
        name = sys.argv[sys.argv.index("--one") + 1]
        fn = next(f for n, _, f in ALL_BENCHES if n == name)
        import jax
        jax.devices()    # device contact proven before the first beat
        _hb()
        _enable_compile_cache()
        if "--write" in sys.argv:
            # published numbers are TPU numbers: refuse to overwrite them
            # from an off-TPU run (BENCH_PLATFORM smoke tests, CPU
            # fallback), and fail LOUDLY if the baseline file is unreadable
            # — a silent no-op would mark the burst stage done with the
            # measurement lost. Both checks run BEFORE the measurement so a
            # doomed run refuses in milliseconds, not after a 30-min bench
            backend = jax.default_backend()
            if backend not in ("tpu", "axon"):
                print(f"# --write refused: backend is {backend!r}, not TPU",
                      file=sys.stderr)
                sys.exit(3)
            if _read_baseline()[0] is None:
                print("# --write failed: BASELINE.json missing/unreadable",
                      file=sys.stderr)
                sys.exit(3)
        value = round(fn(), 1)
        if "--write" in sys.argv:
            base_doc, _ = _read_baseline()
            if base_doc is None:   # deleted mid-run: still fail loudly
                print("# --write failed: BASELINE.json missing/unreadable",
                      file=sys.stderr)
                sys.exit(3)
            _write_partial(base_doc, {name: value})
        print(json.dumps({"one": name, "value": value,
                          # backend-reachability provenance: False only
                          # when this number was measured on real TPU
                          # hardware (see _backend_stale)
                          "stale": _backend_stale(),
                          "monitor": _monitor_snapshot(),
                          "jitwatch": _jitwatch_snapshot(),
                          # prefetch-off/on ETL comparison — populated only
                          # by the input_pipeline config, None elsewhere
                          "input_pipeline": INPUT_PIPELINE_STATS or None,
                          # 1-server-dense vs N-server-delta comparison —
                          # populated only by the paramserver config
                          "paramserver": PARAMSERVER_STATS or None,
                          # sync-vs-overlap latency-hiding comparison
                          # (injected push delay, per-phase means) —
                          # populated only by the paramserver_overlap
                          # config
                          "paramserver_overlap":
                              PARAMSERVER_OVERLAP_STATS or None,
                          # {replicated, ws, fsdp} × {1-D, 2-D} mesh grid —
                          # populated only by the parallel_memory config
                          "parallel_memory": PARALLEL_MEMORY_STATS or None,
                          # offered-QPS sweep (p50/p99/reject/batch-size) —
                          # populated only by the serving_latency config
                          "serving": SERVING_STATS or None,
                          # cold-vs-warm compile-cache warmup comparison
                          # (compile-once fleet) — populated only by the
                          # serving_latency config's cold-start mode
                          "cold_start": COLD_START_STATS or None,
                          # chaos-drill recovery telemetry (closed-loop
                          # control plane) — populated only by the
                          # control_loop config
                          "control_loop": CONTROL_LOOP_STATS or None,
                          # scrape-plane collector cost over K HTTP
                          # replicas — populated only by the
                          # fleet_scrape config
                          "fleet_scrape": FLEET_SCRAPE_STATS or None,
                          # probe-plane interference on serving p99 at
                          # 1-4 probe QPS — populated only by the
                          # probe_overhead config
                          "probe_overhead": PROBE_OVERHEAD_STATS or None,
                          # incident-plane interference on the chaos
                          # drill's serving p99 (recorder off vs on) —
                          # populated only by the incident_overhead
                          # config
                          "incident_overhead":
                              INCIDENT_OVERHEAD_STATS or None,
                          # whole-package tpulint wall time (all rules,
                          # shipped baseline) — populated only by the
                          # lint_full config
                          "lint_full": LINT_FULL_STATS or None}))
        return

    run_all = "--all" in sys.argv
    # startup replay FIRST (defense 1), then the signal/deadline guards
    # (defenses 2+3). --all runs under the burst harness's own horizon, so
    # the hard deadline is off there unless explicitly set.
    base_doc, base_val = _emit_startup_replay()
    default_deadline = 0 if run_all else 1440
    _install_guards(float(os.environ.get("BENCH_DEADLINE_S",
                                         default_deadline)))
    if not _await_backend():
        # fail honestly rather than hang the driver: no number is fabricated;
        # the stale replay (if any) is marked as such and the exit code is
        # non-zero. BASELINE.json keeps the last real measurements.
        sys.exit(_emit_final(error="TPU backend init hang (wedged tunnel); "
                                   "no fresh measurement taken"))

    if run_all:
        results = {}
        for name, unit, fn in ALL_BENCHES:
            value = _run_one_subprocess(name)
            if value is None:
                # one config failed/hung — reprobe (shorter window) so the
                # remaining configs still get their chance if the tunnel
                # recovers, then move on
                if not _await_backend(max_wait_s=600):
                    print("# backend still down; skipping remaining configs",
                          file=sys.stderr)
                    break
                continue
            results[name] = value
            print(f"# {name}: {value} {unit}", file=sys.stderr)
            # write ONLY the new entry: passing the cumulative dict would
            # re-stamp earlier configs' last_measured with the wrong time
            _write_partial(base_doc, {name: value})
            if name == "resnet50_imagenet_images_per_sec":
                # latch immediately: a SIGTERM later in the sweep must emit
                # THIS fresh number, not the previous round's stale replay
                _FINAL["fresh_value"] = value
        value = results.get("resnet50_imagenet_images_per_sec")
    else:
        value = _run_one_subprocess("resnet50_imagenet_images_per_sec",
                                    timeout_s=_budget_timeout(2400))
        if value is None and _await_backend(
                max_wait_s=min(600, _budget_timeout(600))):
            value = _run_one_subprocess("resnet50_imagenet_images_per_sec",
                                        timeout_s=_budget_timeout(2400))
        if value is not None:
            _FINAL["fresh_value"] = value      # latch before any disk I/O
            # bank the fresh headline + its timestamp (default mode is the
            # driver's path — its numbers must persist like --all's do)
            _write_partial(base_doc,
                           {"resnet50_imagenet_images_per_sec": value})

    if value is None:
        sys.exit(_emit_final(error="benchmark did not complete (wedged "
                                   "tunnel?); no fresh measurement"))
    _FINAL["fresh_value"] = value
    sys.exit(_emit_final())


if __name__ == "__main__":
    main()
