"""Benchmark harness: prints ONE JSON line
``{"metric", "value", "unit", "vs_baseline"}``.

Measured config — the BASELINE.json north star: ResNet50 (deeplearning4j-zoo
ComputationGraph architecture) training on synthetic ImageNet-shaped input
(the reference's ``BenchmarkDataSetIterator`` pattern), images/sec on one
chip. The whole train step (forward, AD backward, updater, param update) is a
single jitted XLA computation; params in f32, matmul/conv compute in bfloat16
on the MXU with f32 accumulation.

Throughput accounting matches the reference's ``PerformanceListener``
(samples/sec). The reference publishes no numbers (BASELINE.md), so
``vs_baseline`` is the ratio against ``published`` in BASELINE.json when
present, else 1.0.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    # batch 256: v5e is HBM-bandwidth-bound on ResNet50; smaller batches
    # under-amortize fixed per-step work (PERF.md has the batch sweep)
    batch = 256
    warmup, iters = 3, 10

    model = ResNet50(num_classes=1000)
    conf = model.conf()
    conf.global_conf.compute_dtype = "bfloat16"  # MXU path, f32 accumulation
    net = ComputationGraph(conf).init()

    rng = np.random.default_rng(0)
    f = jnp.asarray(rng.normal(size=(batch, 3, 224, 224)), jnp.float32)
    l = jnp.asarray(np.eye(1000, dtype=np.float32)[rng.integers(0, 1000,
                                                                batch)])

    step = net._ensure_step()
    params, states, upd = net.params, net.states, net.updater_state
    key = jax.random.PRNGKey(0)
    for i in range(warmup):
        it = jnp.asarray(i, jnp.int32)
        params, states, upd, loss = step(params, states, upd, it, key, (f,),
                                         (l,), None, None)
    loss.block_until_ready()

    t0 = time.perf_counter()
    for i in range(warmup, warmup + iters):
        it = jnp.asarray(i, jnp.int32)
        params, states, upd, loss = step(params, states, upd, it, key, (f,),
                                         (l,), None, None)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    images_per_sec = batch * iters / dt
    try:
        with open("BASELINE.json") as fh:
            published = json.load(fh).get("published", {})
        base = published.get("resnet50_imagenet_images_per_sec")
    except Exception:
        base = None
    vs = images_per_sec / base if base else 1.0
    print(json.dumps({"metric": "resnet50_imagenet_images_per_sec",
                      "value": round(images_per_sec, 1),
                      "unit": "images/sec",
                      "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
