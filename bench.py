"""Benchmark harness.

Default run prints ONE JSON line — the BASELINE.json north-star metric
(ResNet50 ComputationGraph training, images/sec on one chip). ``--all`` also
benchmarks every config BASELINE.md commits to (LeNet MNIST, VGG16, GravesLSTM
char-RNN with TBPTT, Word2Vec skip-gram, Keras-imported inception-style model
under ParallelWrapper), writes the results into ``BASELINE.json.published``,
and still prints the single ResNet50 JSON line last.

Throughput accounting matches the reference's ``PerformanceListener``
(samples/sec; ``optimize/listeners/PerformanceListener.java:22-23``). Synthetic
inputs follow the reference's ``BenchmarkDataSetIterator`` pattern. The whole
train step (forward, AD backward, updater, param update) is a single jitted
XLA computation; params in f32, matmul/conv compute in bfloat16 on the MXU
(see PERF.md for the measurement史 and the roofline analysis).
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def _sync(x):
    """Reliable completion barrier: materialize the VALUE of (a leaf of) ``x``
    on the host. Under the axon TPU tunnel ``jax.block_until_ready`` can
    return before the device program finishes (measured: a VGG16 train step
    "completing" in 0.4 ms), so timing must gate on an actual device→host
    value transfer — the loss scalar, whose value transitively requires every
    queued step's compute."""
    import jax
    leaf = jax.tree_util.tree_leaves(x)[-1]
    return np.asarray(leaf)


def _time_steps(step_fn, n_warmup=3, n_timed=10):
    """Run ``step_fn(i)`` (must return a device value whose VALUE depends on
    the step's compute — the loss) and return the timed-phase duration."""
    out = None
    for i in range(n_warmup):
        out = step_fn(i)
    _sync(out)
    t0 = time.perf_counter()
    for i in range(n_warmup, n_warmup + n_timed):
        out = step_fn(i)
    _sync(out)
    return time.perf_counter() - t0


def _cnn_throughput(model_cls, batch, img, classes=1000, iters=10,
                    compute_dtype="bfloat16", **model_kw):
    """images/sec for a zoo CNN (ComputationGraph or MultiLayerNetwork) on
    synthetic data."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf.graph import ComputationGraphConfiguration
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    model = model_cls(num_classes=classes, **model_kw)
    conf = model.conf()
    conf.global_conf.compute_dtype = compute_dtype
    is_graph = isinstance(conf, ComputationGraphConfiguration)
    net = (ComputationGraph(conf) if is_graph
           else MultiLayerNetwork(conf)).init()
    rng = np.random.default_rng(0)
    c, h, w = img
    f = jnp.asarray(rng.normal(size=(batch, c, h, w)), jnp.float32)
    l = jnp.asarray(np.eye(classes, dtype=np.float32)[
        rng.integers(0, classes, batch)])
    step = net._ensure_step()
    state = {"p": net.params, "s": net.states, "u": net.updater_state}
    key = jax.random.PRNGKey(0)

    feats = (f,) if is_graph else f
    labels = (l,) if is_graph else l

    def one(i):
        it = jnp.asarray(i, jnp.int32)
        state["p"], state["s"], state["u"], loss = step(
            state["p"], state["s"], state["u"], it, key, feats, labels,
            None, None)
        return loss

    dt = _time_steps(one, n_timed=iters)
    return batch * iters / dt


def bench_resnet50(batch=256):
    # batch 256: v5e is HBM-bandwidth-bound on ResNet50; smaller batches
    # under-amortize fixed per-step work (PERF.md has the batch sweep).
    # 25 timed iters: single runs of 10 showed a ~5% run-to-run band
    from deeplearning4j_tpu.models import ResNet50
    return _cnn_throughput(ResNet50, batch, (3, 224, 224), iters=25)


def bench_vgg16(batch=256):
    # batch 256: 1403 img/s = 126 TFLOPS = 64% MFU by XLA's flop count
    # (22.98 TF / 69.9 GB per step) — compute-bound; 128 gives 1311
    from deeplearning4j_tpu.models import VGG16
    return _cnn_throughput(VGG16, batch, (3, 224, 224))


def bench_lenet(batch=1024, n_iter=10, fits=10):
    """LeNet MNIST (MultiLayerNetwork) images/sec through the public fit
    path, using the framework's own small-model configs: ``iterations(10)``
    (reference 0.9.x multi-iteration minibatch, compiled here as ONE scanned
    XLA program) + ``CacheMode.DEVICE`` (HBM-resident batch). Without them
    LeNet is dispatch-latency-bound (~13 ms/step over the tunnel vs 1.1 ms
    scanned)."""
    from deeplearning4j_tpu.models import LeNet
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.datasets.dataset import DataSet

    conf = LeNet(num_classes=10).conf()
    conf.global_conf.compute_dtype = "bfloat16"
    conf.global_conf.cache_mode = "device"
    conf.global_conf.iterations = n_iter
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ds = DataSet(rng.normal(size=(batch, 1, 28, 28)).astype(np.float32),
                 np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])
    net.fit(ds)
    _sync(net.score_)
    t0 = time.perf_counter()
    for _ in range(fits):
        net.fit(ds)
    _sync(net.score_)
    return batch * fits * n_iter / (time.perf_counter() - t0)


def bench_graves_lstm(batch=64, seq_len=200, tbptt=50, vocab=80, width=512):
    """GravesLSTM char-RNN with TBPTT (the reference CudnnLSTMHelper's
    showcase config): characters/sec processed."""
    import jax
    import jax.numpy as jnp
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, BackpropType
    from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu import Adam

    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Adam(learning_rate=1e-3)).activation("tanh")
            .compute_dtype("bfloat16")
            .cache_mode("device")  # epoch reuse: one H2D, HBM-resident after
            .list()
            .layer(GravesLSTM(n_in=vocab, n_out=width))
            .layer(GravesLSTM(n_in=width, n_out=width))
            .layer(RnnOutputLayer(n_in=width, n_out=vocab,
                                  activation="softmax", loss="mcxent"))
            .build())
    conf.backprop_type = BackpropType.TruncatedBPTT
    conf.tbptt_fwd_length = tbptt
    conf.tbptt_back_length = tbptt
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    ids = rng.integers(0, vocab, size=(batch, seq_len))
    f = np.eye(vocab, dtype=np.float32)[ids]          # [b, T, vocab]
    l = np.eye(vocab, dtype=np.float32)[np.roll(ids, -1, axis=1)]
    from deeplearning4j_tpu.datasets.dataset import DataSet

    ds = DataSet(f, l)
    net.fit(ds)  # warmup/compile all TBPTT segment shapes
    _sync(net.score_)
    n = 3
    t0 = time.perf_counter()
    for _ in range(n):
        net.fit(ds)
    _sync(net.score_)  # value fetch: transitively waits on every segment step
    dt = time.perf_counter() - t0
    return batch * seq_len * n / dt


def bench_word2vec(n_sentences=20000, sent_len=40, vocab_target=5000):
    """Word2Vec skip-gram (HS) words/sec through the jitted kernels.
    800k-word corpus so steady-state batch throughput dominates the one-time
    vocab build + kernel compile (PerformanceListener-style accounting)."""
    from deeplearning4j_tpu.nlp import Word2Vec

    rng = np.random.default_rng(0)
    zipf = rng.zipf(1.3, size=n_sentences * sent_len) % vocab_target
    words = zipf.reshape(n_sentences, sent_len)
    sentences = [" ".join(f"w{t}" for t in row) for row in words]
    w2v = Word2Vec(vector_length=128, window=5, epochs=1, batch_size=8192,
                   min_word_frequency=1)
    t0 = time.perf_counter()
    w2v.fit(sentences)
    dt = time.perf_counter() - t0
    return n_sentences * sent_len / dt


def bench_keras_import_parallel(batch_per_step=256, iters=10):
    """Keras-imported inception-style ComputationGraph trained under
    ParallelWrapper (BASELINE.md config 6; single chip → one worker, the
    multi-chip path is exercised by the virtual-mesh dryrun)."""
    import os
    import jax
    from deeplearning4j_tpu.keras.model_import import KerasModelImport
    from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMode
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator

    path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tests",
                        "resources", "keras", "functional_inception.h5")
    net = KerasModelImport.import_keras_model_and_weights(path)
    net.gc.compute_dtype = "bfloat16"
    rng = np.random.default_rng(0)
    n_dev = len(jax.devices())
    dsets = [DataSet(rng.normal(size=(batch_per_step // n_dev, 3, 16, 16)
                                ).astype(np.float32),
                     np.eye(6, dtype=np.float32)[
                         rng.integers(0, 6, batch_per_step // n_dev)])
             for _ in range(n_dev)]
    pw = (ParallelWrapper.Builder(net).training_mode(TrainingMode.AVERAGING)
          .averaging_frequency(1).build())
    pw.fit(ListDataSetIterator(dsets))  # compile + one pass
    _sync(net.params)
    t0 = time.perf_counter()
    for _ in range(iters):
        pw.fit(ListDataSetIterator(dsets))
    # value-fetch a param leaf (pw.last_score is already a host float);
    # axon block_until_ready is unreliable — see _sync
    _sync(net.params)
    dt = time.perf_counter() - t0
    return batch_per_step * iters / dt


ALL_BENCHES = [
    ("lenet_mnist_images_per_sec", "images/sec", bench_lenet),
    ("resnet50_imagenet_images_per_sec", "images/sec", bench_resnet50),
    ("vgg16_imagenet_images_per_sec", "images/sec", bench_vgg16),
    ("graves_lstm_charrnn_chars_per_sec", "chars/sec", bench_graves_lstm),
    ("word2vec_skipgram_words_per_sec", "words/sec", bench_word2vec),
    ("keras_inception_parallelwrapper_images_per_sec", "images/sec",
     bench_keras_import_parallel),
]


def _await_backend(attempts=4, probe_timeout=120, retry_wait=120) -> bool:
    """Guard against a wedged axon tunnel: PJRT client creation can hang
    FOREVER when the relay holds a stale lease (observed twice in round 3,
    PERF.md addendum). Probe ``jax.devices()`` in a subprocess under a
    timeout, retrying a few times (the tunnel has recovered on its own
    before); return False instead of letting the benchmark hang."""
    import subprocess

    for i in range(attempts):
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, timeout=probe_timeout)
            if probe.returncode == 0:
                return True
        except subprocess.TimeoutExpired:
            pass
        last = i == attempts - 1
        print(f"# TPU backend unreachable (attempt {i + 1}/{attempts})"
              + ("" if last else f"; retrying in {retry_wait}s"),
              file=sys.stderr)
        if not last:
            time.sleep(retry_wait)
    return False


def main():
    run_all = "--all" in sys.argv
    if not _await_backend():
        # fail FAST and honestly rather than hang the driver: no number is
        # fabricated; the error is machine-readable and the exit code is
        # non-zero. BASELINE.json keeps the last real measurements.
        print(json.dumps({"metric": "resnet50_imagenet_images_per_sec",
                          "value": None, "unit": "images/sec",
                          "vs_baseline": None,
                          "error": "TPU backend init hang (wedged tunnel); "
                                   "no measurement taken"}))
        sys.exit(2)
    # prior published baseline read BEFORE any update — vs_baseline compares
    # against the previous round's number, not the one this run writes
    try:
        with open("BASELINE.json") as fh:
            base_doc = json.load(fh)
        base_val = base_doc.get("published", {}).get(
            "resnet50_imagenet_images_per_sec")
    except Exception:
        base_doc, base_val = None, None

    results = {}
    if run_all:
        for name, unit, fn in ALL_BENCHES:
            try:
                results[name] = round(fn(), 1)
                print(f"# {name}: {results[name]} {unit}", file=sys.stderr)
            except Exception as e:  # keep the headline metric alive
                print(f"# {name} FAILED: {e}", file=sys.stderr)
        if base_doc is not None:
            base_doc.setdefault("published", {}).update(results)
            with open("BASELINE.json", "w") as fh:
                json.dump(base_doc, fh, indent=2)
        value = results.get("resnet50_imagenet_images_per_sec")
    else:
        value = round(bench_resnet50(), 1)

    vs = (value / base_val) if (base_val and value) else 1.0
    print(json.dumps({"metric": "resnet50_imagenet_images_per_sec",
                      "value": value,
                      "unit": "images/sec",
                      "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
