"""Benchmark harness: trains the flagship config on-device and prints ONE JSON
line ``{"metric", "value", "unit", "vs_baseline"}``.

Measured config (BASELINE.json ``configs[0]``): LeNet MNIST MultiLayerNetwork,
synthetic MNIST-shaped input (the reference's synthetic-benchmark pattern,
``BenchmarkDataSetIterator.java``). Throughput accounting matches the
reference's ``PerformanceListener`` (samples/sec).

The reference publishes no numbers (BASELINE.md), so ``vs_baseline`` is the
ratio against the recorded target in BASELINE.json ``published`` when present,
else 1.0.
"""
from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from __graft_entry__ import _lenet

    batch = 256
    warmup, iters = 5, 30

    net = _lenet()
    rng = np.random.default_rng(0)
    f = jnp.asarray(rng.normal(size=(batch, 1, 28, 28)), jnp.float32)
    l = jnp.asarray(np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)])

    step = net._ensure_step()
    params, states, upd = net.params, net.states, net.updater_state
    key = jax.random.PRNGKey(0)
    for i in range(warmup):
        it = jnp.asarray(i, jnp.int32)
        params, states, upd, loss = step(params, states, upd, it, key, f, l,
                                         None, None)
    loss.block_until_ready()

    t0 = time.perf_counter()
    for i in range(warmup, warmup + iters):
        it = jnp.asarray(i, jnp.int32)
        params, states, upd, loss = step(params, states, upd, it, key, f, l,
                                         None, None)
    loss.block_until_ready()
    dt = time.perf_counter() - t0

    images_per_sec = batch * iters / dt
    try:
        with open("BASELINE.json") as fh:
            published = json.load(fh).get("published", {})
        base = published.get("lenet_mnist_images_per_sec")
    except Exception:
        base = None
    vs = images_per_sec / base if base else 1.0
    print(json.dumps({"metric": "lenet_mnist_images_per_sec",
                      "value": round(images_per_sec, 1),
                      "unit": "images/sec",
                      "vs_baseline": round(vs, 3)}))


if __name__ == "__main__":
    main()
