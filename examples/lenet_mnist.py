"""LeNet on MNIST — the reference's canonical first example
(deeplearning4j-examples LenetMnistExample), TPU-native: the whole train
step (fwd + AD bwd + Adam + apply) is one compiled XLA program.

Run: python examples/lenet_mnist.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                InputType, Adam)
from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer, DenseLayer,
                                               OutputLayer, PoolingType,
                                               SubsamplingLayer)
from deeplearning4j_tpu.datasets.impl import MnistDataSetIterator
from deeplearning4j_tpu.optimize.listeners import (PerformanceListener,
                                                   ScoreIterationListener)


def main():
    conf = (NeuralNetConfiguration.builder()
            .seed(123)
            .updater(Adam(learning_rate=1e-3))
            .activation("relu")
            .list()
            .layer(ConvolutionLayer(n_out=20, kernel_size=(5, 5)))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(ConvolutionLayer(n_out=50, kernel_size=(5, 5)))
            .layer(SubsamplingLayer(pooling_type=PoolingType.MAX,
                                    kernel_size=(2, 2), stride=(2, 2)))
            .layer(DenseLayer(n_out=500))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.convolutional_flat(28, 28, 1))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.set_listeners(ScoreIterationListener(10), PerformanceListener(10))
    print(net.summary())

    train = MnistDataSetIterator(batch=128, train=True)
    test = MnistDataSetIterator(batch=512, train=False)
    net.fit(train, epochs=1)
    print(net.evaluate(test).stats())


if __name__ == "__main__":
    main()
