"""ResNet50 under ParallelWrapper — the reference's multi-GPU showcase
(deeplearning4j-examples MultiGpuLenetMnistExample pattern at ResNet scale),
TPU-native: the batch shards over the mesh `data` axis and XLA's SPMD
partitioner fuses the gradient all-reduce (psum over ICI) into the one
compiled train step.

Run: python examples/resnet50_data_parallel.py
(On a single chip the mesh has one device; on a pod slice it uses them all.)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMode
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator


def main():
    model = ResNet50(num_classes=1000)
    conf = model.conf()
    conf.global_conf.compute_dtype = "bfloat16"  # MXU path
    net = ComputationGraph(conf).init()

    rng = np.random.default_rng(0)
    batches = [DataSet(rng.normal(size=(32, 3, 224, 224)).astype(np.float32),
                       np.eye(1000, dtype=np.float32)[
                           rng.integers(0, 1000, 32)])
               for _ in range(4)]

    pw = (ParallelWrapper.Builder(net)
          .training_mode(TrainingMode.AVERAGING)
          .averaging_frequency(1)
          .build())
    pw.fit(ListDataSetIterator(batches))
    print("score:", pw.last_score)


if __name__ == "__main__":
    main()
