"""ResNet50 under ParallelWrapper — the reference's multi-GPU showcase
(deeplearning4j-examples MultiGpuLenetMnistExample pattern at ResNet scale),
TPU-native: the batch shards over the mesh `data` axis and XLA's SPMD
partitioner fuses the gradient all-reduce (psum over ICI) into the one
compiled train step.

Run: python examples/resnet50_data_parallel.py
(On a single chip the mesh has one device; on a pod slice it uses them all.)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import numpy as np

from deeplearning4j_tpu.models import ResNet50
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel import ParallelWrapper, TrainingMode
from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator


def main():
    # DL4J_TPU_EXAMPLE_SMALL=1 shrinks to a CPU-smoke footprint; the
    # default is the TPU-sized ImageNet config
    small = bool(os.environ.get("DL4J_TPU_EXAMPLE_SMALL"))
    classes, hw, b = (10, 64, 8) if small else (1000, 224, 32)
    model = ResNet50(num_classes=classes,
                     input_shape=(3, hw, hw) if small else None)
    conf = model.conf()
    conf.global_conf.compute_dtype = "bfloat16"  # MXU path
    net = ComputationGraph(conf).init()

    rng = np.random.default_rng(0)
    batches = [DataSet(rng.normal(size=(b, 3, hw, hw)).astype(np.float32),
                       np.eye(classes, dtype=np.float32)[
                           rng.integers(0, classes, b)])
               for _ in range(4)]

    # DL4J_TPU_EXAMPLE_FSDP=1: ZeRO-3-style sharded storage — params AND
    # optimizer state live 1/N per device (exact same numerics); ws-only
    # (optimizer state) via .weight_update_sharding()
    builder = (ParallelWrapper.Builder(net)
               .training_mode(TrainingMode.AVERAGING)
               .averaging_frequency(1))
    if os.environ.get("DL4J_TPU_EXAMPLE_FSDP"):
        builder.fsdp()
    pw = builder.build()
    pw.fit(ListDataSetIterator(batches))
    print("score:", pw.last_score)
    if os.environ.get("DL4J_TPU_EXAMPLE_FSDP"):
        import jax
        sharded = sum(1 for l in jax.tree_util.tree_leaves(net.params)
                      if hasattr(l, "sharding") and l.sharding.spec)
        print(f"FSDP: {sharded} param leaves sharded over the data axis")


if __name__ == "__main__":
    main()
