"""Shared example bootstrap.

``maybe_force_cpu()`` honors two knobs BEFORE the first framework import
(environment variables alone are too late — the interpreter's
sitecustomize may pin a TPU platform at startup, so the override has to
go through ``jax.config``):

- ``DL4J_TPU_EXAMPLE_CPU=1``  — run the example on the CPU backend.
- ``DL4J_TPU_EXAMPLE_CPU=N``  (N > 1) — virtual N-device CPU mesh, so the
  parallel examples exercise their sharding without TPU hardware.

Combine with ``DL4J_TPU_EXAMPLE_SMALL=1`` for a quick smoke footprint.
"""
import os


def maybe_force_cpu():
    v = os.environ.get("DL4J_TPU_EXAMPLE_CPU", "").strip().lower()
    if v in ("", "0", "false", "no", "off"):
        return
    try:
        n = int(v)
    except ValueError:
        n = 1
    from deeplearning4j_tpu.compat import set_cpu_devices

    set_cpu_devices(max(n, 1))
