"""Transfer learning — the reference's TransferLearning.Builder flow
(deeplearning4j-examples TransferLearningExample): freeze a trained
feature extractor, replace the head, fine-tune on a new task.

Run: python examples/transfer_learning.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import numpy as np

from deeplearning4j_tpu import (NeuralNetConfiguration, MultiLayerNetwork,
                                DataSet, Adam)
from deeplearning4j_tpu.nn.conf.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.transferlearning import (FineTuneConfiguration,
                                                    TransferLearning)


def main():
    rng = np.random.default_rng(0)

    # 1) "pretrained" base model: 3-class task
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Adam(learning_rate=5e-3)).activation("relu")
            .list()
            .layer(DenseLayer(n_in=8, n_out=32))
            .layer(DenseLayer(n_in=32, n_out=16))
            .layer(OutputLayer(n_in=16, n_out=3, activation="softmax",
                               loss="mcxent"))
            .build())
    base = MultiLayerNetwork(conf).init()
    f = rng.normal(size=(256, 8)).astype(np.float32)
    l = np.eye(3, dtype=np.float32)[np.abs(f[:, :3]).argmax(1)]
    for _ in range(40):
        base.fit(DataSet(f, l))
    print(f"base model trained: score {float(base.score_):.4f}")

    # 2) transfer: freeze layers 0-1, swap the head for a 5-class task
    new_net = (TransferLearning.Builder(base)
               .fine_tune_configuration(
                   FineTuneConfiguration(updater=Adam(learning_rate=5e-3)))
               .set_feature_extractor(1)      # freeze up to layer 1
               .n_out_replace(2, 5)            # new 5-way output head
               .build())
    f2 = rng.normal(size=(256, 8)).astype(np.float32)
    # new 5-way labeling that reuses the base features (classes 0-2 occur)
    l2 = np.eye(5, dtype=np.float32)[np.abs(f2[:, :3]).argmax(1)]
    frozen_before = np.asarray(new_net.params["0"]["W"]).copy()
    for _ in range(150):
        new_net.fit(DataSet(f2, l2))
    frozen_after = np.asarray(new_net.params["0"]["W"])
    print(f"fine-tuned: score {float(new_net.score_):.4f}; "
          f"frozen layer unchanged: {np.array_equal(frozen_before, frozen_after)}")
    from deeplearning4j_tpu import ListDataSetIterator
    print("accuracy:",
          new_net.evaluate(ListDataSetIterator([DataSet(f2, l2)])).accuracy())


if __name__ == "__main__":
    main()
