"""Pipeline-parallel training of a zoo model (container-level GPipe).

TextGenerationLSTM's stacked identical cells map onto pipeline stages;
entry/head stay replicated; with a 2-D mesh the microbatch dim is also
data-parallel. Runs on any mesh — including the virtual CPU mesh:

    DL4J_TPU_EXAMPLE_CPU=8 python examples/pipeline_parallel_lstm.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import numpy as np
import jax

from deeplearning4j_tpu.models import TextGenerationLSTM
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.parallel import pipeline_parallel_step, make_mesh

VOCAB, WIDTH, CELLS = 47, 32, 5           # 4 identical middle cells

net = MultiLayerNetwork(
    TextGenerationLSTM(total_unique_characters=VOCAB, lstm_size=WIDTH,
                       num_layers=CELLS).conf()).init()

n = len(jax.devices())
body = CELLS - 1                 # identical middle cells available as stages
pipe = max(s for s in range(1, min(n, body) + 1)
           if n % s == 0 and body % s == 0)   # feasible stage count
mesh = make_mesh(jax.devices(), axes=("pipe", "data"),
                 shape=(pipe, n // pipe))
pp = pipeline_parallel_step(net, mesh, n_microbatches=4,
                            data_axis="data" if n // pipe > 1 else None)
print(f"stages={pp.n_stages} layers/stage={pp.layers_per_stage} "
      f"entry={pp.start} body={pp.body_len}")

rng = np.random.default_rng(0)
ids = rng.integers(0, VOCAB, size=(16, 16))
f = np.eye(VOCAB, dtype=np.float32)[ids]
l = np.eye(VOCAB, dtype=np.float32)[np.roll(ids, -1, axis=1)]

for step in range(10):
    loss = pp.fit_batch(f, l)
    if step % 5 == 0:
        print(f"step {step:3d} loss {float(loss):.4f}")

net.params = pp.export_params()           # back into the container
print("sampled logits shape:", np.asarray(net.output(f[:2])).shape)
