"""Keras model import — the reference's deeplearning4j-modelimport flow:
save any tf.keras model to legacy HDF5, import it as a TPU-native network,
fine-tune or serve it.

Run: python examples/keras_import.py  (needs tensorflow to build the h5)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import numpy as np


def main():
    import tensorflow as tf

    m = tf.keras.Sequential([
        tf.keras.layers.Input((8, 8, 3)),
        tf.keras.layers.Conv2D(8, 3, activation="relu", name="c1"),
        tf.keras.layers.MaxPooling2D(2, name="p1"),
        tf.keras.layers.Flatten(name="f"),
        tf.keras.layers.Dense(10, activation="softmax", name="out"),
    ])
    m.compile(loss="categorical_crossentropy", optimizer="adam")
    m.save("/tmp/keras_model.h5")

    from deeplearning4j_tpu.keras.model_import import KerasModelImport
    net = KerasModelImport.import_keras_sequential_model_and_weights(
        "/tmp/keras_model.h5")
    x = np.random.default_rng(0).normal(size=(4, 3, 8, 8)).astype(np.float32)
    print("imported; output shape:", np.asarray(net.output(x)).shape)
    # TPU f32 matmuls run as bf16 passes by default → ~1e-3 abs tolerance
    # (the CPU golden tests pin 1e-5; tests/test_keras_golden.py)
    print("matches Keras:", np.allclose(
        np.asarray(net.output(x)),
        m.predict(np.transpose(x, (0, 2, 3, 1)), verbose=0), atol=5e-3))


if __name__ == "__main__":
    main()
