"""GravesLSTM character-level language model with truncated BPTT — the
reference's GravesLSTMCharModellingExample, TPU-native (scan-compiled LSTM,
bf16 MXU gemms, CacheMode.DEVICE keeps the corpus HBM-resident).

Run: python examples/char_rnn.py [path/to/corpus.txt]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()


import numpy as np

from deeplearning4j_tpu import NeuralNetConfiguration, MultiLayerNetwork, Adam
from deeplearning4j_tpu.nn.conf import BackpropType
from deeplearning4j_tpu.nn.conf.layers import GravesLSTM, RnnOutputLayer
from deeplearning4j_tpu.datasets.dataset import DataSet

TEXT = ("the quick brown fox jumps over the lazy dog. "
        "pack my box with five dozen liquor jugs. ") * 200


def main():
    text = (open(sys.argv[1]).read() if len(sys.argv) > 1 else TEXT)
    chars = sorted(set(text))
    idx = {c: i for i, c in enumerate(chars)}
    V, T, B = len(chars), 100, 32

    conf = (NeuralNetConfiguration.builder().seed(12345)
            .updater(Adam(learning_rate=1e-3)).activation("tanh")
            .compute_dtype("bfloat16").cache_mode("device")
            .list()
            .layer(GravesLSTM(n_in=V, n_out=256))
            .layer(GravesLSTM(n_in=256, n_out=256))
            .layer(RnnOutputLayer(n_in=256, n_out=V, activation="softmax",
                                  loss="mcxent"))
            .build())
    conf.backprop_type = BackpropType.TruncatedBPTT
    conf.tbptt_fwd_length = conf.tbptt_back_length = 50
    net = MultiLayerNetwork(conf).init()

    ids = np.array([idx[c] for c in text[:B * (T + 1)]]).reshape(B, T + 1)
    f = np.eye(V, dtype=np.float32)[ids[:, :-1]]
    l = np.eye(V, dtype=np.float32)[ids[:, 1:]]
    ds = DataSet(f, l)
    for epoch in range(10):
        net.fit(ds)
        print(f"epoch {epoch}: score {float(net.score_):.4f}")

    # sample with the streaming rnn_time_step API
    net.rnn_clear_previous_state()
    x = np.zeros((1, 1, V), np.float32)
    x[0, 0, idx["t"]] = 1
    out = ["t"]
    rng = np.random.default_rng(0)
    for _ in range(80):
        p = np.asarray(net.rnn_time_step(x))[0, 0]
        c = rng.choice(V, p=p / p.sum())
        out.append(chars[c])
        x = np.zeros((1, 1, V), np.float32)
        x[0, 0, c] = 1
    print("sample:", "".join(out))


if __name__ == "__main__":
    main()
