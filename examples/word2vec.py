"""Word2Vec skip-gram — the reference's Word2VecRawTextExample, TPU-native:
pair generation is vectorized on host, updates run as batched jitted kernels
with HBM-resident Huffman tables and single-transfer batches.

Run: python examples/word2vec.py [path/to/corpus.txt]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()


from deeplearning4j_tpu.nlp import Word2Vec, CollectionSentenceIterator

SENTENCES = ["the king rules the kingdom", "the queen rules the kingdom",
             "a dog chases a cat", "a cat chases a mouse",
             "the king and the queen wear crowns"] * 200


def main():
    sentences = (open(sys.argv[1]).read().splitlines()
                 if len(sys.argv) > 1 else SENTENCES)
    w2v = (Word2Vec.builder()
           .layer_size(100).window_size(5).min_word_frequency(2)
           .epochs(3).seed(42)
           .iterate(CollectionSentenceIterator(sentences))
           .build())
    w2v.fit()
    print("king ~ queen:", w2v.similarity("king", "queen"))
    print("nearest to king:", w2v.words_nearest("king", 5))


if __name__ == "__main__":
    main()
