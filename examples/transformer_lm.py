"""TransformerLM: the TPU build's net-new decoder-only language model.

The 0.9.x reference's only sequence model is ``TextGenerationLSTM``
(``zoo/model/TextGenerationLSTM.java``) — it predates transformers. This
example trains the zoo's ``TransformerLM`` (pre-LN residual blocks built as
a ComputationGraph: EmbeddingSequence → n × [SelfAttention + gelu FFN] →
LayerNormalization → softmax) on a toy copy task, then shows the same model
training with its TIME dim sharded across devices via
``sequence_parallel_step`` — rank-2 ``[b, T]`` token-id inputs are
recognized as temporal and shard on dim 1.

Run on CPU:  DL4J_TPU_EXAMPLE_CPU=8 python examples/transformer_lm.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.models import TransformerLM
from deeplearning4j_tpu.datasets.dataset import MultiDataSet
from deeplearning4j_tpu.parallel import (sequence_parallel_step, make_mesh,
                                         SEQUENCE_AXIS)

VOCAB, T, BATCH = 32, 64, 8

rng = np.random.default_rng(0)
ids = rng.integers(0, VOCAB, size=(BATCH, T))
labels = np.eye(VOCAB, dtype=np.float32)[np.roll(ids, -1, axis=1)]

# ---- single-device training through the normal container API -------------
model = TransformerLM(vocab_size=VOCAB, embed_dim=64, num_heads=4,
                      num_blocks=2, seed=7)
net = model.init()
mds = MultiDataSet((ids.astype(np.float32),), (labels,))
print("initial score:", float(net.score(mds)))
for epoch in range(20):
    net.fit(mds)
print("trained score:", float(net.score(mds)))

# autoregressive sampling through the KV cache
from deeplearning4j_tpu.models import generate_tokens

sample = generate_tokens(net, ids[:2, :8], n_tokens=12, temperature=0.8,
                         seed=1)
print("sampled continuation:", sample[0].tolist())

# ---- the same model, time dim sharded over all devices (sp) ---------------
devices = jax.devices()
if len(devices) >= 2 and T % len(devices) == 0:
    mesh = make_mesh(devices, axes=(SEQUENCE_AXIS,))
    sp_net = TransformerLM(vocab_size=VOCAB, embed_dim=64, num_heads=4,
                           num_blocks=2, seed=7).init()
    step, place = sequence_parallel_step(sp_net, mesh)
    place(sp_net)
    f = jnp.asarray(ids, jnp.float32)
    l = jnp.asarray(labels)
    for it in range(20):
        sp_net.params, sp_net.states, sp_net.updater_state, loss = step(
            sp_net.params, sp_net.states, sp_net.updater_state,
            jnp.asarray(it, jnp.int32), jax.random.PRNGKey(it), (f,), (l,))
    print(f"sp-trained loss over {len(devices)} time shards:", float(loss))

# ---- pipeline parallelism: residual blocks as GPipe stages ----------------
if len(devices) >= 2:
    from deeplearning4j_tpu.parallel import pipeline_parallel_step

    pp_net = TransformerLM(vocab_size=VOCAB, embed_dim=64, num_heads=4,
                           num_blocks=4, seed=7).init()
    pp = pipeline_parallel_step(pp_net, make_mesh(devices[:2],
                                                  axes=("pipe",)),
                                n_microbatches=2)
    for _ in range(20):
        pp_loss = pp.fit_batch(ids.astype(np.float32), labels)
    print("pp-trained loss (residual blocks over 2 stages):",
          float(pp_loss))
