"""Long-context training with container-level sequence parallelism.

A transformer-style net trains with the TIME dimension sharded across the
mesh — ring(-flash) attention mixes context across shards, so per-device
activation memory is O(T/n) while the math stays exactly the full-attention
step. Runs anywhere; to try it on the virtual CPU mesh:

    DL4J_TPU_EXAMPLE_CPU=8 python examples/long_context_sequence_parallel.py

(env-var platform overrides alone are too late when a sitecustomize pins
the TPU backend; the knob routes through jax.config before import)
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from _common import maybe_force_cpu  # noqa: E402

maybe_force_cpu()

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu import NeuralNetConfiguration, Adam
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf.layers import (SelfAttentionLayer, DenseLayer,
                                               RnnOutputLayer)
from deeplearning4j_tpu.parallel import (sequence_parallel_step, make_mesh,
                                         SEQUENCE_AXIS)

VOCAB, WIDTH, HEADS = 32, 64, 4

conf = (NeuralNetConfiguration.builder().seed(7)
        .updater(Adam(learning_rate=3e-4)).activation("identity")
        .list()
        .layer(SelfAttentionLayer(n_in=VOCAB, n_out=WIDTH, num_heads=HEADS,
                                  causal=True))
        .layer(DenseLayer(n_in=WIDTH, n_out=WIDTH, activation="relu"))
        .layer(SelfAttentionLayer(n_in=WIDTH, n_out=WIDTH, num_heads=HEADS,
                                  causal=True))
        .layer(RnnOutputLayer(n_in=WIDTH, n_out=VOCAB, activation="softmax",
                              loss="mcxent"))
        .build())
net = MultiLayerNetwork(conf).init()

n = len(jax.devices())
mesh = make_mesh(jax.devices(), axes=(SEQUENCE_AXIS,))
step, place = sequence_parallel_step(net, mesh)
place(net)

T = 128 * n                       # local shard = 128 → flash-in-ring on TPU
rng = np.random.default_rng(0)
ids = rng.integers(0, VOCAB, size=(2, T))
f = np.eye(VOCAB, dtype=np.float32)[ids]
l = np.eye(VOCAB, dtype=np.float32)[np.roll(ids, -1, axis=1)]
print(f"devices={n}  T={T}  local shard={T // n}")

it = 0
for s in range(10):
    (net.params, net.states, net.updater_state, loss) = step(
        net.params, net.states, net.updater_state,
        jnp.asarray(it, jnp.int32), jax.random.PRNGKey(s),
        jnp.asarray(f), jnp.asarray(l))
    it += 1
    if s % 3 == 0:
        print(f"step {s:2d} loss {float(loss):.3f}")

# after sp training the same net serves with the normal dense path
out = net.output(f[:, :64])
print("dense-path inference after sp training:", np.asarray(out).shape)
