"""Perf experiment harness (not part of the framework; PERF.md records results).

Batch-size sweep over the ResNet50 train step — the measurement loop behind
the PERF.md table. `python perf_exp.py 64 128 256`.
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp


def bench_resnet(batch=256, iters=10, warmup=3, compute_dtype="bfloat16"):
    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    model = ResNet50(num_classes=1000)
    conf = model.conf()
    conf.global_conf.compute_dtype = compute_dtype
    net = ComputationGraph(conf).init()

    rng = np.random.default_rng(0)
    f = jnp.asarray(rng.normal(size=(batch, 3, 224, 224)), jnp.float32)
    l = jnp.asarray(np.eye(1000, dtype=np.float32)[rng.integers(0, 1000, batch)])

    step = net._ensure_step()
    params, states, upd = net.params, net.states, net.updater_state
    key = jax.random.PRNGKey(0)
    for i in range(warmup):
        it = jnp.asarray(i, jnp.int32)
        params, states, upd, loss = step(params, states, upd, it, key, (f,), (l,), None, None)
    float(loss)  # value fetch: axon block_until_ready can return early
    t0 = time.perf_counter()
    for i in range(warmup, warmup + iters):
        it = jnp.asarray(i, jnp.int32)
        params, states, upd, loss = step(params, states, upd, it, key, (f,), (l,), None, None)
    float(loss)  # value fetch: axon block_until_ready can return early
    dt = time.perf_counter() - t0
    ips = batch * iters / dt
    print(f"batch={batch} dtype={compute_dtype}: {ips:.1f} img/s "
          f"({dt / iters * 1e3:.1f} ms/step)")
    return ips


if __name__ == "__main__":
    for b in (int(x) for x in sys.argv[1:] or ["256"]):
        bench_resnet(batch=b)
