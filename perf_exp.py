"""Perf experiment harness (not part of the framework; PERF.md records results).

Modes (run on real TPU; the burst harness drives `full`):

  python perf_exp.py 64 128 256      # batch-size sweep (legacy spelling)
  python perf_exp.py sweep 64 256    # same, explicit
  python perf_exp.py remat           # VERDICT r4 item 8: batch 384/512,
                                     # remat off vs auto (HBM-wall push)
  python perf_exp.py cost [BATCH]    # XLA cost model + v5e roofline bound
  python perf_exp.py full            # cost + sweep + remat (burst stage)
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp


def _setup(batch, compute_dtype="bfloat16", remat="off"):
    """One model+data builder for bench AND cost — the cost model must
    lower exactly the program the benchmark runs."""
    from deeplearning4j_tpu.models import ResNet50
    from deeplearning4j_tpu.nn.graph import ComputationGraph

    conf = ResNet50(num_classes=1000).conf()
    conf.global_conf.compute_dtype = compute_dtype
    conf.global_conf.remat = remat
    net = ComputationGraph(conf).init()
    rng = np.random.default_rng(0)
    f = jnp.asarray(rng.normal(size=(batch, 3, 224, 224)), jnp.float32)
    l = jnp.asarray(np.eye(1000, dtype=np.float32)[
        rng.integers(0, 1000, batch)])
    return net, f, l


def bench_resnet(batch=256, iters=10, warmup=3, compute_dtype="bfloat16",
                 remat="off"):
    net, f, l = _setup(batch, compute_dtype, remat)
    step = net._ensure_step()
    params, states, upd = net.params, net.states, net.updater_state
    key = jax.random.PRNGKey(0)
    for i in range(warmup):
        it = jnp.asarray(i, jnp.int32)
        params, states, upd, loss = step(params, states, upd, it, key, (f,), (l,), None, None)
    float(loss)  # value fetch: axon block_until_ready can return early
    t0 = time.perf_counter()
    for i in range(warmup, warmup + iters):
        it = jnp.asarray(i, jnp.int32)
        params, states, upd, loss = step(params, states, upd, it, key, (f,), (l,), None, None)
    float(loss)  # value fetch: axon block_until_ready can return early
    dt = time.perf_counter() - t0
    ips = batch * iters / dt
    print(f"batch={batch} dtype={compute_dtype} remat={remat}: "
          f"{ips:.1f} img/s ({dt / iters * 1e3:.1f} ms/step)")
    return ips


def remat_ab():
    """VERDICT r4 item 8: push past the HBM wall — larger batches amortize
    fixed traffic but blow activation memory; remat='auto' (saveable
    conv/gemm outputs, recompute the cheap elementwise chains) trades
    recompute FLOPs for HBM. Keep or revert BY MEASUREMENT; failures
    (OOM) are recorded, not fatal."""
    for batch in (384, 512):
        for remat in ("off", "auto"):
            try:
                bench_resnet(batch=batch, remat=remat)
            except Exception as e:
                print(f"batch={batch} remat={remat} FAILED: "
                      f"{str(e)[:200]}", flush=True)


def cost(batch=256, remat="off"):
    """XLA cost model of the ResNet50 train step + v5e roofline bound
    (197 TFLOPS bf16, 819 GB/s HBM) — the before/after instrument for any
    layout/fusion change."""
    net, f, l = _setup(batch, remat=remat)
    step = net._ensure_step()
    lowered = step.lower(net.params, net.states, net.updater_state,
                         jnp.asarray(0, jnp.int32), jax.random.PRNGKey(0),
                         (f,), (l,), None, None)
    ca = lowered.compile().cost_analysis() or {}
    flops = float(ca.get("flops", 0.0))
    by = float(ca.get("bytes accessed", 0.0))
    t_f, t_h = flops / 197e12, by / 819e9
    if max(t_f, t_h) == 0.0:
        # cost_analysis unavailable on this backend/jaxlib: report, don't
        # crash the burst stage
        print(f"batch={batch} remat={remat}: cost_analysis unavailable")
        return
    bound = "HBM" if t_h > t_f else "compute"
    print(f"batch={batch} remat={remat}: {flops/1e12:.2f} TFLOP, "
          f"{by/1e9:.1f} GB/step -> ideal {batch/max(t_f, t_h):,.0f} img/s "
          f"({bound}-bound)")


def main(argv):
    if not argv or argv[0].isdigit():
        for b in (int(x) for x in argv or ["256"]):
            bench_resnet(batch=b)
    elif argv[0] == "sweep":
        for b in (int(x) for x in argv[1:] or ["64", "128", "256"]):
            bench_resnet(batch=b)
    elif argv[0] == "remat":
        remat_ab()
    elif argv[0] == "cost":
        cost(int(argv[1]) if len(argv) > 1 else 256)
        cost(int(argv[1]) if len(argv) > 1 else 256, remat="auto")
    elif argv[0] == "full":
        cost(256)
        cost(512, remat="auto")
        for b in (128, 256):
            bench_resnet(batch=b)
        remat_ab()
    elif argv[0] == "bench2":
        for b in (128, 256):
            bench_resnet(batch=b)
    else:
        raise SystemExit(f"unknown mode {argv[0]}")


if __name__ == "__main__":
    main(sys.argv[1:])
